"""End-to-end: client verbs -> full control plane -> event-watch observability.

The in-process analog of the reference's kind e2e tier
(e2e/armadactl_test/armadactl_test.go): a user submits via the server,
the system runs, and the user observes outcomes purely through the Event API.
"""

import pytest

from armada_tpu.server import JobSubmitItem, QueueRecord
from tests.control_plane import ControlPlane


@pytest.fixture(params=[False, True], ids=["legacy", "incremental"])
def cp(tmp_path, request):
    """Both problem-build paths (per-cycle and cycle-persistent incremental,
    scheduler.go:240-246 analog) must drive the full stack identically."""
    from armada_tpu.core.config import SchedulingConfig

    plane = ControlPlane.build(
        tmp_path,
        config=SchedulingConfig(
            shape_bucket=32,
            enable_assertions=True,
            incremental_problem_build=request.param,
        ),
    )
    plane.server.create_queue(QueueRecord("tenant-a", weight=2.0))
    plane.server.create_queue(QueueRecord("tenant-b", weight=1.0))
    yield plane
    plane.close()


def item(cpu="2", **kw):
    return JobSubmitItem(resources={"cpu": cpu, "memory": "2"}, **kw)


def test_full_lifecycle_observed_via_event_api(cp):
    ids = cp.server.submit_jobs("tenant-a", "batch-1", [item(), item()])
    cp.run_until(
        lambda: all(s == "succeeded" for s in cp.job_states().values())
        and len(cp.job_states()) == 2,
        tick_s=3.0,
    )

    # The event stream tells the whole story, in order.
    kinds = [
        ev.WhichOneof("event")
        for e in cp.event_api.get_jobset_events("tenant-a", "batch-1")
        for ev in e.sequence.events
    ]
    for expected in (
        "submit_job",
        "job_validated",
        "job_run_leased",
        "job_run_running",
        "job_run_succeeded",
        "job_succeeded",
    ):
        assert kinds.count(expected) == 2, (expected, kinds)
    # ordering per kind: submit before lease before success
    assert kinds.index("submit_job") < kinds.index("job_run_leased")
    assert kinds.index("job_run_leased") < kinds.index("job_succeeded")


def test_cancel_mid_flight_via_server(cp):
    ids = cp.server.submit_jobs("tenant-a", "batch-2", [item()])
    cp.run_until(lambda: cp.job_states().get(ids[0]) == "leased")
    cp.server.cancel_jobs("tenant-a", "batch-2", ids, reason="changed my mind")
    cp.run_until(lambda: cp.job_states().get(ids[0]) == "cancelled")
    # the pod is gone from every executor
    assert all(not ex.cluster.pod_states() for ex in cp.executors)


def test_preempt_via_server_requeues_nothing_and_fails_job(cp):
    ids = cp.server.submit_jobs("tenant-a", "batch-3", [item()])
    cp.run_until(lambda: cp.job_states().get(ids[0]) == "leased")
    cp.server.preempt_jobs("tenant-a", "batch-3", ids, reason="ops")
    cp.run_until(lambda: cp.job_states().get(ids[0]) == "failed")
    kinds = [
        ev.WhichOneof("event")
        for e in cp.event_api.get_jobset_events("tenant-a", "batch-3")
        for ev in e.sequence.events
    ]
    assert "job_run_preempted" in kinds


def test_weighted_fair_share_between_tenants(cp):
    # Saturate: each tenant submits 16 x 2cpu; capacity is 2 nodes x 8 cpu.
    cp.server.submit_jobs("tenant-a", "fair", [item() for _ in range(16)])
    cp.server.submit_jobs("tenant-b", "fair", [item() for _ in range(16)])
    for ex in cp.executors:
        ex.run_once()  # register nodes with the scheduler
    cp.ingest()
    cp.scheduler.cycle()

    txn = cp.jobdb.read_txn()
    by_queue = {"tenant-a": 0, "tenant-b": 0}
    for job in txn.all_jobs():
        if job.has_active_run():
            by_queue[job.queue] += 1
    # 8 slots; weight 2:1 -> about 5-6 for tenant-a, 2-3 for tenant-b
    assert by_queue["tenant-a"] > by_queue["tenant-b"] >= 2, by_queue
    assert by_queue["tenant-a"] + by_queue["tenant-b"] == 8
