"""Lookout web UI: a single-page jobs dashboard over the lookout query stack.

Plays the role of the reference's lookout UI (internal/lookoutui, React/TS ~18k
LoC): a jobs table with filtering, grouping with per-state counts, job details
with runs and errors -- served as one embedded HTML page + JSON endpoints on a
stdlib HTTP server, backed by LookoutQueries (repository/getjobs.go,
groupjobs.go semantics).

Endpoints:
  GET /                  the app
  GET /api/jobs?...      filtered page of jobs + total count
  GET /api/groups?by=X   grouped counts with per-state breakdown
  GET /api/job/{id}      job details incl. runs
  GET /api/overview      global state counts
  GET /api/logs?job=&run=   pod logs via binoculars (logs.go:39-43); 501
                            when the UI has no binoculars wired
  GET/POST /api/views    server-side saved views (lookout DB saved_view
                            table; the reference UI's server-backed views)
  DELETE /api/views/{name}

Drilldown: grouping by queue and clicking a row descends to jobsets within
that queue; clicking a jobset lands on its job list; a job opens details
with runs and a live log viewer -- queue -> group -> job -> runs -> logs
without the CLI (App.tsx navigation parity).

State colors are the validated categorical theme (dataviz skill reference
palette; adjacency validated in both modes: CVD dE 9.1 light / 8.4 dark);
identity is never color-alone -- every segment and chip carries the state name
and count as text, and the table is the primary view.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, unquote, urlparse

from armada_tpu.lookout.db import JOB_STATES
from armada_tpu.lookout.queries import JobFilter, JobOrder, LookoutQueries

# Fixed state -> hue assignment in the theme's validated adjacency order
# (the meter renders segments in exactly this order).
STATE_ORDER = (
    "RUNNING", "PREEMPTED", "LEASED", "QUEUED",
    "PENDING", "SUCCEEDED", "CANCELLED", "FAILED",
)
STATE_COLORS_LIGHT = {
    "RUNNING": "#2a78d6", "PREEMPTED": "#eb6834", "LEASED": "#1baf7a",
    "QUEUED": "#eda100", "PENDING": "#e87ba4", "SUCCEEDED": "#008300",
    "CANCELLED": "#4a3aa7", "FAILED": "#e34948",
}
STATE_COLORS_DARK = {
    "RUNNING": "#3987e5", "PREEMPTED": "#d95926", "LEASED": "#199e70",
    "QUEUED": "#c98500", "PENDING": "#d55181", "SUCCEEDED": "#008300",
    "CANCELLED": "#9085e9", "FAILED": "#e66767",
}

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>armada-tpu lookout</title>
<style>
:root {
  color-scheme: light;
  --surface: #fcfcfb; --surface-2: #f0efec; --border: #dcdbd6;
  --text: #0b0b0b; --text-2: #52514e;
__LIGHT_VARS__
}
@media (prefers-color-scheme: dark) {
  :root:not([data-theme="light"]) {
    color-scheme: dark;
    --surface: #1a1a19; --surface-2: #262624; --border: #3a3a37;
    --text: #ffffff; --text-2: #c3c2b7;
__DARK_VARS__
  }
}
:root[data-theme="dark"] {
  color-scheme: dark;
  --surface: #1a1a19; --surface-2: #262624; --border: #3a3a37;
  --text: #ffffff; --text-2: #c3c2b7;
__DARK_VARS__
}
* { box-sizing: border-box; }
body { margin: 0; background: var(--surface); color: var(--text);
       font: 13px/1.45 system-ui, sans-serif; }
header { display: flex; align-items: center; gap: 12px; padding: 10px 16px;
         border-bottom: 1px solid var(--border); }
header h1 { font-size: 15px; margin: 0; font-weight: 600; }
header .sub { color: var(--text-2); }
main { padding: 12px 16px; max-width: 1280px; margin: 0 auto; }
.filters { display: flex; flex-wrap: wrap; gap: 8px; margin-bottom: 12px; }
.filters input, .filters select, .filters button, header button {
  background: var(--surface); color: var(--text); border: 1px solid var(--border);
  border-radius: 6px; padding: 5px 8px; font: inherit; }
.filters button, header button { cursor: pointer; }
.meter { display: flex; height: 14px; border-radius: 4px; overflow: hidden;
         background: var(--surface-2); margin: 4px 0 6px; }
.meter span { height: 100%; }
.meter span + span { margin-left: 2px; }  /* 2px surface gap between fills */
.chips { display: flex; flex-wrap: wrap; gap: 6px 14px; margin-bottom: 14px; }
.chip { color: var(--text-2); white-space: nowrap; }
.chip b { color: var(--text); font-weight: 600; }
.dot { display: inline-block; width: 9px; height: 9px; border-radius: 50%;
       margin-right: 5px; vertical-align: -1px; }
table { border-collapse: collapse; width: 100%; }
th, td { text-align: left; padding: 5px 10px; border-bottom: 1px solid var(--border); }
th { color: var(--text-2); font-weight: 500; cursor: pointer; user-select: none;
     white-space: nowrap; }
tbody tr:hover { background: var(--surface-2); }
tbody tr { cursor: pointer; }
.num { text-align: right; font-variant-numeric: tabular-nums; }
.mini { display: flex; height: 10px; border-radius: 3px; overflow: hidden;
        background: var(--surface-2); min-width: 160px; }
.mini span + span { margin-left: 2px; }
#details { position: fixed; top: 0; right: 0; width: min(480px, 90vw);
           height: 100vh; background: var(--surface); border-left: 1px solid var(--border);
           padding: 16px; overflow: auto; display: none; box-shadow: -4px 0 24px #0003; }
#details.open { display: block; }
#details h2 { font-size: 14px; margin: 0 0 8px; word-break: break-all; }
#details dl { display: grid; grid-template-columns: auto 1fr; gap: 2px 12px; }
#details dt { color: var(--text-2); }
#details pre { background: var(--surface-2); padding: 8px; border-radius: 6px;
               white-space: pre-wrap; word-break: break-all; }
.run { border: 1px solid var(--border); border-radius: 6px; padding: 8px;
       margin: 6px 0; }
.crumbs { display: flex; flex-wrap: wrap; gap: 6px; margin-bottom: 8px; }
.crumbs:empty { display: none; }
.crumb { background: var(--surface-2); border: 1px solid var(--border);
         border-radius: 12px; padding: 2px 10px; cursor: pointer; }
.crumb:hover { border-color: var(--text-2); }
.logbox { margin-top: 6px; }
.logbox pre { max-height: 320px; overflow: auto; }
.logbtn { background: var(--surface); color: var(--text); cursor: pointer;
          border: 1px solid var(--border); border-radius: 6px; padding: 3px 8px; }
.pager { display: flex; gap: 8px; align-items: center; margin-top: 10px;
         color: var(--text-2); }
.pager button { background: var(--surface); color: var(--text);
  border: 1px solid var(--border); border-radius: 6px; padding: 4px 10px; cursor: pointer; }
.empty { color: var(--text-2); padding: 24px; text-align: center; }
</style></head>
<body>
<header>
  <h1>armada-tpu lookout</h1><span class="sub" id="total"></span>
  <span style="flex:1"></span>
  <button id="theme" title="toggle light/dark">◐</button>
</header>
<main>
  <div class="meter" id="overview" role="img" aria-label="job state distribution"></div>
  <div class="chips" id="chips"></div>
  <div class="filters">
    <input id="f-queue" placeholder="queue contains…">
    <input id="f-jobset" placeholder="jobset contains…">
    <select id="f-state"><option value="">any state</option>__STATE_OPTIONS__</select>
    <input id="f-ann" placeholder="annotation key=value (or key=*)" title="filter by annotation; key=* matches any value">
    <select id="f-group">
      <option value="">no grouping</option>
      <option value="queue">group by queue</option>
      <option value="jobset">group by jobset</option>
      <option value="state">group by state</option>
      <option value="annotation">group by annotation…</option>
    </select>
    <input id="f-groupkey" placeholder="annotation key" style="display:none">
    <button id="refresh">refresh</button>
    <label class="chip"><input type="checkbox" id="auto" checked> auto (3s)</label>
    <select id="views"><option value="">saved views…</option></select>
    <button id="save-view" title="save the current filters as a named view (server-side)">save view</button>
    <button id="del-view" title="delete the selected view">✕ view</button>
  </div>
  <div class="crumbs" id="crumbs"></div>
  <div id="content"></div>
  <div class="pager" id="pager"></div>
</main>
<div id="details"></div>
<script>
const COLORS = __COLORS_JSON__;
const ORDER = __ORDER_JSON__;
const dark = () => document.documentElement.dataset.theme === "dark" ||
  (!document.documentElement.dataset.theme &&
   matchMedia("(prefers-color-scheme: dark)").matches);
const color = (s) => COLORS[dark() ? "dark" : "light"][s] || "#999";
let skip = 0, take = 50, orderField = "submitted", orderDir = "DESC";
let contentSeq = 0, overviewSeq = 0;  // drop stale responses
// drilldown trail: [{field, value, group}] -- group is the grouping that was
// active when the crumb was pushed, restored when the crumb is popped
let drill = [];

const $ = (id) => document.getElementById(id);
const fmtT = (ns) => ns ? new Date(ns / 1e6).toLocaleString() : "—";
const esc = (s) => String(s ?? "").replace(/[&<>"]/g,
  (c) => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));

function filterQS() {
  const p = new URLSearchParams();
  if ($("f-queue").value) p.set("queue", $("f-queue").value);
  if ($("f-jobset").value) p.set("jobset", $("f-jobset").value);
  if ($("f-state").value) p.set("state", $("f-state").value);
  const ann = $("f-ann").value.trim();
  if (ann && ann.includes("=")) {
    const i = ann.indexOf("=");
    p.set("ann." + ann.slice(0, i).trim(), ann.slice(i + 1).trim() || "*");
  }
  return p;
}

// --- saved views (server-side: lookout DB saved_view table) ---------------
let serverViews = {};
async function loadViews() {
  try {
    const d = await j("/api/views");
    serverViews = Object.fromEntries(
      d.views.map((v) => [v.name, JSON.parse(v.payload)]));
  } catch (e) { serverViews = {}; }
  renderViews();
}
function renderViews() {
  const sel = $("views").value;
  $("views").innerHTML = '<option value="">saved views…</option>' +
    Object.keys(serverViews).sort().map((n) =>
      `<option value="${esc(n)}">${esc(n)}</option>`).join("");
  if (serverViews[sel] !== undefined) $("views").value = sel;
}
function applyView(v) {
  for (const [id, val] of Object.entries(v)) { if ($(id)) $(id).value = val; }
  $("f-groupkey").style.display =
    $("f-group").value === "annotation" ? "" : "none";
  drill = [];
  refresh();
}
async function j(url) { const r = await fetch(url); return r.json(); }

function meterHTML(states, total) {
  if (!total) return "";
  return ORDER.filter((s) => states[s])
    .map((s) => `<span style="flex:${states[s]};background:${color(s)}"
      title="${s}: ${states[s]}"></span>`).join("");
}
function chipsHTML(states) {
  return ORDER.filter((s) => states[s]).map((s) =>
    `<span class="chip"><span class="dot" style="background:${color(s)}"></span>` +
    `${s.toLowerCase()} <b>${states[s]}</b></span>`).join("") ||
    '<span class="chip">no jobs yet</span>';
}
async function loadOverview() {
  const my = ++overviewSeq;
  const d = await j("/api/overview");
  if (my !== overviewSeq) return;  // a newer request superseded this one
  const total = Object.values(d.states).reduce((a, b) => a + b, 0);
  $("overview").innerHTML = meterHTML(d.states, total);
  $("chips").innerHTML = chipsHTML(d.states);
  $("total").textContent = total + " jobs";
}
function stateCell(s) {
  return `<span class="dot" style="background:${color(s)}"></span>${s.toLowerCase()}`;
}
async function loadContent() {
  const my = ++contentSeq;
  const group = $("f-group").value;
  if (group === "annotation" && !$("f-groupkey").value.trim()) {
    $("content").innerHTML = '<div class="empty">enter an annotation key to group by</div>';
    $("pager").innerHTML = "";
    return;
  }
  if (group) {
    const keyQ = group === "annotation"
      ? `&key=${encodeURIComponent($("f-groupkey").value.trim())}` : "";
    const d = await j(`/api/groups?by=${group}&take=500${keyQ}&` + filterQS());
    if (my !== contentSeq) return;
    $("pager").innerHTML = "";
    if (!d.groups.length) { $("content").innerHTML = '<div class="empty">nothing matches</div>'; return; }
    const note = d.truncated
      ? `<div class="empty">showing the ${d.groups.length} largest groups — refine the filters to see the rest</div>`
      : "";
    $("content").innerHTML = `<table><thead><tr><th>${esc(group)}</th>
      <th class="num">jobs</th><th>states</th></tr></thead><tbody>` +
      d.groups.map((g) => {
        const total = g.count;
        return `<tr data-group="${esc(g.group)}"><td>${esc(g.group)}</td>
          <td class="num">${g.count}</td>
          <td><div class="mini">${meterHTML(g.states, total)}</div></td></tr>`;
      }).join("") + "</tbody></table>" + note;
    for (const tr of $("content").querySelectorAll("tr[data-group]")) {
      tr.onclick = () => {
        const v = tr.dataset.group;
        if (group === "state") { $("f-state").value = v; $("f-group").value = ""; }
        else if (group === "annotation") {
          $("f-ann").value = $("f-groupkey").value.trim() + "=" + v;
          $("f-group").value = "";
        } else if (group === "queue") {
          // drill: queue -> its jobsets -> job list
          drill.push({field: "f-queue", value: v, group});
          $("f-queue").value = v;
          $("f-group").value = "jobset";
        } else {
          drill.push({field: "f-jobset", value: v, group});
          $("f-jobset").value = v;
          $("f-group").value = "";
        }
        skip = 0;
        refresh();
      };
    }
    return;
  }
  const p = filterQS();
  p.set("skip", skip); p.set("take", take);
  p.set("order", orderField); p.set("dir", orderDir);
  const d = await j("/api/jobs?" + p);
  if (my !== contentSeq) return;
  if (!d.jobs.length && d.total > 0 && skip > 0) {
    // the filtered total shrank under our page cursor: snap back
    skip = Math.max(0, (Math.ceil(d.total / take) - 1) * take);
    return loadContent();
  }
  if (!d.jobs.length) { $("content").innerHTML = '<div class="empty">nothing matches</div>'; $("pager").innerHTML = ""; return; }
  const arrow = (f) => orderField === f ? (orderDir === "ASC" ? " ↑" : " ↓") : "";
  $("content").innerHTML = `<table><thead><tr>
      <th data-o="job_id">job${arrow("job_id")}</th>
      <th data-o="queue">queue${arrow("queue")}</th>
      <th data-o="jobset">jobset${arrow("jobset")}</th>
      <th data-o="state">state${arrow("state")}</th>
      <th class="num" data-o="priority">priority${arrow("priority")}</th>
      <th data-o="submitted">submitted${arrow("submitted")}</th>
      <th>node</th></tr></thead><tbody>` +
    d.jobs.map((r) => `<tr data-id="${esc(r.job_id)}">
      <td>${esc(r.job_id)}</td><td>${esc(r.queue)}</td><td>${esc(r.jobset)}</td>
      <td>${stateCell(r.state)}</td><td class="num">${r.priority}</td>
      <td>${fmtT(r.submitted_ns)}</td><td>${esc(r.node || "—")}</td></tr>`).join("") +
    "</tbody></table>";
  for (const th of $("content").querySelectorAll("th[data-o]")) {
    th.onclick = () => {
      if (orderField === th.dataset.o) orderDir = orderDir === "ASC" ? "DESC" : "ASC";
      else { orderField = th.dataset.o; orderDir = "ASC"; }
      refresh();
    };
  }
  for (const tr of $("content").querySelectorAll("tr[data-id]"))
    tr.onclick = () => openDetails(tr.dataset.id);
  const page = Math.floor(skip / take) + 1;
  const pages = Math.max(1, Math.ceil(d.total / take));
  $("pager").innerHTML = `<button id="prev" ${skip ? "" : "disabled"}>‹ prev</button>
    <span>page ${page} / ${pages} (${d.total} jobs)</span>
    <button id="next" ${skip + take < d.total ? "" : "disabled"}>next ›</button>`;
  if ($("prev")) $("prev").onclick = () => { skip = Math.max(0, skip - take); refresh(); };
  if ($("next")) $("next").onclick = () => { skip += take; refresh(); };
}
const logTimers = new Map();  // run id -> live-tail interval (one per box)
function stopLogTimer(runId) {
  if (logTimers.has(runId)) { clearInterval(logTimers.get(runId)); logTimers.delete(runId); }
}
function stopAllLogTimers() { for (const id of [...logTimers.keys()]) stopLogTimer(id); }
async function fetchLogs(jobId, runId, boxId) {
  const box = $(boxId);
  if (!box) { stopLogTimer(runId); return; }
  const r = await fetch(`/api/logs?job=${encodeURIComponent(jobId)}&run=${encodeURIComponent(runId)}`);
  const d = await r.json();
  const pre = box.querySelector("pre");
  if (!pre) return;
  const atEnd = pre.scrollTop + pre.clientHeight >= pre.scrollHeight - 4;
  pre.textContent = r.ok ? (d.log || "(empty)") : `⚠ ${d.error}`;
  if (atEnd) pre.scrollTop = pre.scrollHeight;  // follow the tail
}
function openLogs(jobId, runId, live) {
  const boxId = "log-" + runId;
  const box = $(boxId);
  if (!box) return;
  if (box.innerHTML) {  // toggle off
    box.innerHTML = "";
    stopLogTimer(runId);
    return;
  }
  box.innerHTML = "<pre>loading…</pre>";
  fetchLogs(jobId, runId, boxId);
  stopLogTimer(runId);
  if (live) logTimers.set(runId, setInterval(() => fetchLogs(jobId, runId, boxId), 3000));
}
async function openDetails(id) {
  const d = await j("/api/job/" + encodeURIComponent(id));
  if (!d) return;
  const live = new Set(["LEASED", "PENDING", "RUNNING"]);
  const runs = (d.runs || []).map((r) => `<div class="run">
    <div><b>run</b> ${esc(r.run_id)} — ${stateCell(r.state)}
      <button class="logbtn" data-run="${esc(r.run_id)}"
        data-live="${live.has(r.state) ? 1 : ""}">logs${live.has(r.state) ? " (live)" : ""}</button></div>
    <dl><dt>node</dt><dd>${esc(r.node || "—")}</dd>
    <dt>leased</dt><dd>${fmtT(r.leased_ns)}</dd>
    <dt>started</dt><dd>${fmtT(r.started_ns)}</dd>
    <dt>finished</dt><dd>${fmtT(r.finished_ns)}</dd></dl>
    ${r.error ? `<pre>${esc(r.error)}</pre>` : ""}
    <div class="logbox" id="log-${esc(r.run_id)}"></div></div>`).join("");
  $("details").innerHTML = `<h2>${esc(d.job_id)}</h2>
    <dl><dt>state</dt><dd>${stateCell(d.state)}</dd>
    <dt>queue</dt><dd>${esc(d.queue)}</dd>
    <dt>jobset</dt><dd>${esc(d.jobset)}</dd>
    <dt>priority</dt><dd>${d.priority}</dd>
    <dt>submitted</dt><dd>${fmtT(d.submitted_ns)}</dd>
    <dt>annotations</dt><dd><pre>${esc(JSON.stringify(d.annotations || {}, null, 1))}</pre></dd></dl>
    <h2>runs</h2>${runs || '<div class="empty">no runs</div>'}
    <button id="close-details">close</button>`;
  for (const b of $("details").querySelectorAll(".logbtn"))
    b.onclick = () => openLogs(d.job_id, b.dataset.run, !!b.dataset.live);
  $("close-details").onclick = () => {
    $("details").classList.remove("open");
    stopAllLogTimers();
  };
  $("details").classList.add("open");
}
function renderCrumbs() {
  $("crumbs").innerHTML = drill.map((c, i) =>
    `<span class="crumb" data-i="${i}" title="back to this level">` +
    `${esc(c.field === "f-queue" ? "queue" : "jobset")}: ${esc(c.value)} ✕</span>`
  ).join("");
  for (const el of $("crumbs").querySelectorAll(".crumb")) {
    el.onclick = () => {
      const i = +el.dataset.i;
      // pop this crumb and everything after it; restore its grouping level
      const popped = drill[i];
      for (const c of drill.slice(i)) $(c.field).value = "";
      drill = drill.slice(0, i);
      $("f-group").value = popped.group;
      skip = 0;
      refresh();
    };
  }
}
function refresh() { renderCrumbs(); loadOverview(); loadContent(); }
$("refresh").onclick = refresh;
for (const id of ["f-queue", "f-jobset", "f-state", "f-group", "f-ann", "f-groupkey"])
  $(id).addEventListener("change", () => {
    skip = 0;
    // manual edits invalidate any drilldown crumb they contradict
    drill = drill.filter((c) => $(c.field).value === c.value);
    refresh();
  });
$("f-group").addEventListener("change", () => {
  $("f-groupkey").style.display =
    $("f-group").value === "annotation" ? "" : "none";
});
$("save-view").onclick = async () => {
  const name = prompt("view name:");
  if (!name) return;
  const payload = Object.fromEntries(
    ["f-queue", "f-jobset", "f-state", "f-ann", "f-group", "f-groupkey"]
      .map((id) => [id, $(id).value]));
  await fetch("/api/views", {
    method: "POST", headers: {"Content-Type": "application/json"},
    body: JSON.stringify({name, payload}),
  });
  await loadViews();
  $("views").value = name;
};
$("del-view").onclick = async () => {
  const name = $("views").value;
  if (!name || !confirm(`delete view "${name}"?`)) return;
  await fetch("/api/views/" + encodeURIComponent(name), {method: "DELETE"});
  $("views").value = "";
  await loadViews();
};
$("views").addEventListener("change", () => {
  const v = serverViews[$("views").value];
  if (v) applyView(v);
});
loadViews();
$("theme").onclick = () => {
  const r = document.documentElement;
  r.dataset.theme = dark() ? "light" : "dark";
  refresh();
};
setInterval(() => { if ($("auto").checked && !$("details").classList.contains("open")) refresh(); }, 3000);
refresh();
</script>
</body></html>
"""


def _render_page() -> str:
    light_vars = "\n".join(
        f"  --state-{s.lower()}: {c};" for s, c in STATE_COLORS_LIGHT.items()
    )
    dark_vars = "\n".join(
        f"    --state-{s.lower()}: {c};" for s, c in STATE_COLORS_DARK.items()
    )
    options = "".join(f'<option value="{s}">{s.lower()}</option>' for s in JOB_STATES)
    return (
        _PAGE.replace("__LIGHT_VARS__", light_vars)
        .replace("__DARK_VARS__", dark_vars)
        .replace("__STATE_OPTIONS__", options)
        .replace(
            "__COLORS_JSON__",
            json.dumps({"light": STATE_COLORS_LIGHT, "dark": STATE_COLORS_DARK}),
        )
        .replace("__ORDER_JSON__", json.dumps(list(STATE_ORDER)))
    )


def _filters_from_query(qs: dict) -> list[JobFilter]:
    filters = []
    if qs.get("queue"):
        filters.append(JobFilter("queue", qs["queue"][0], "contains"))
    if qs.get("jobset"):
        filters.append(JobFilter("jobset", qs["jobset"][0], "contains"))
    if qs.get("state"):
        filters.append(JobFilter("state", qs["state"][0]))
    # annotation filters: ann.<key>=<value> (exact), ann.<key>=* (exists),
    # annmatch=<mode> applies one of the querybuilder match modes to all
    # annotation terms (querybuilder.go:320-346 parity).
    mode = qs.get("annmatch", ["exact"])[0]
    for param, values in qs.items():
        if param.startswith("ann.") and values:
            key = param[4:]
            if values[0] == "*":
                filters.append(
                    JobFilter("annotation", None, "exists", annotation_key=key)
                )
            else:
                filters.append(
                    JobFilter("annotation", values[0], mode, annotation_key=key)
                )
    return filters


class _Handler(BaseHTTPRequestHandler):
    server_version = "armada-tpu-lookout/1"

    def log_message(self, fmt, *args):
        pass

    def _json(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _authed(self) -> bool:
        """Gate every request on the server's authenticator chain (the same
        server/authn.py chain the gRPC/REST transports use; None = open dev
        default).  Browsers get a Basic challenge; scripts send a bearer.
        A failed/absent credential answers 401 and writes the response."""
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        if srv.authenticator is None:
            return True
        from armada_tpu.server.authn import authenticate_http_headers

        principal, reason = authenticate_http_headers(
            srv.authenticator, self.headers
        )
        if principal is not None:
            return True
        body = json.dumps({"error": f"unauthenticated: {reason}"}).encode()
        self.send_response(401)
        self.send_header("WWW-Authenticate", 'Basic realm="armada-tpu lookout"')
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return False

    def do_GET(self):  # noqa: N802
        if not self._authed():
            return
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        q = srv.queries
        parsed = urlparse(self.path)
        path = parsed.path
        qs = parse_qs(parsed.query)
        try:
            if path == "/":
                body = srv.page.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/api/jobs":
                filters = _filters_from_query(qs)
                order = JobOrder(
                    field=qs.get("order", ["submitted"])[0],
                    direction=qs.get("dir", ["DESC"])[0],
                )
                skip = max(0, int(qs.get("skip", ["0"])[0]))
                take = max(1, min(int(qs.get("take", ["50"])[0]), 500))
                self._json(
                    {
                        "jobs": q.get_jobs(filters, order, skip=skip, take=take),
                        "total": q.count_jobs(filters),
                    }
                )
            elif path == "/api/groups":
                by = qs.get("by", ["queue"])[0]
                take = max(1, min(int(qs.get("take", ["100"])[0]), 500))
                aggs = tuple(
                    qs.get("aggs", ["state"])[0].split(",")
                ) if qs.get("aggs", ["state"])[0] else ()
                # one extra row detects truncation
                groups = q.group_jobs(
                    by,
                    _filters_from_query(qs),
                    aggregates=aggs,
                    take=take + 1,
                    annotation_key=qs.get("key", [""])[0],
                )
                truncated = len(groups) > take
                self._json({"groups": groups[:take], "truncated": truncated})
            elif path == "/api/overview":
                groups = q.group_jobs("state", ())
                states = {g["group"]: g["count"] for g in groups}
                self._json({"states": states})
            elif path.startswith("/api/job/"):
                job_id = path[len("/api/job/") :]
                details = q.get_job_details(job_id)
                if details is None:
                    self._json({"error": f"no job {job_id}"}, 404)
                else:
                    self._json(details)
            elif path == "/api/logs":
                if srv.logs_of is None:
                    self._json(
                        {"error": "no binoculars wired (serve --binoculars-url)"},
                        501,
                    )
                    return
                job_id = qs.get("job", [""])[0]
                run_id = qs.get("run", [""])[0]
                try:
                    self._json(
                        {"log": srv.logs_of(job_id=job_id, run_id=run_id)}
                    )
                except KeyError as exc:
                    self._json({"error": str(exc)}, 404)
                except Exception as exc:  # cluster-side failure, not a 500
                    self._json({"error": f"binoculars: {exc}"}, 502)
            elif path == "/api/views":
                self._json({"views": q.list_views()})
            else:
                self._json({"error": "not found"}, 404)
        except (ValueError, KeyError) as exc:
            self._json({"error": str(exc)}, 400)

    def do_POST(self):  # noqa: N802
        if not self._authed():
            return
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        path = urlparse(self.path).path
        try:
            if path == "/api/views":
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                name = str(body.get("name", ""))
                payload = json.dumps(body.get("payload", {}))
                srv.queries.save_view(name, payload, now_ns=time.time_ns())
                self._json({"ok": True})
            else:
                self._json({"error": "not found"}, 404)
        except (ValueError, KeyError) as exc:
            self._json({"error": str(exc)}, 400)

    def do_DELETE(self):  # noqa: N802
        if not self._authed():
            return
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        path = urlparse(self.path).path
        if path.startswith("/api/views/"):
            name = unquote(path[len("/api/views/") :])
            if srv.queries.delete_view(name):
                self._json({"ok": True})
            else:
                self._json({"error": f"no view {name}"}, 404)
        else:
            self._json({"error": "not found"}, 404)


class LookoutWebUI:
    """Serves the dashboard + JSON API on `port` (0 = pick a free one).

    `logs_of(job_id=..., run_id=...) -> str` supplies pod logs -- wire a
    BinocularsClient.logs (rpc/client.py) or an in-process
    executor.binoculars.Binoculars.logs; None disables the log viewer."""

    def __init__(
        self,
        queries: LookoutQueries,
        port: int = 0,
        host: str = "127.0.0.1",
        logs_of: Optional[Callable] = None,
        authenticator=None,
    ):
        # authenticator: a server/authn.py chain gating the page AND the
        # JSON API (401 + Basic challenge; bearer headers also work).  None
        # keeps the dev default: the page trusts its bind address.  OIDC
        # browser login remains future work -- with an OIDC-only chain, use
        # a bearer-capable client.
        self.queries = queries
        self.logs_of = logs_of
        self.authenticator = authenticator
        self.page = _render_page()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.owner = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()
