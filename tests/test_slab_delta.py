"""Slab delta path equivalence: assemble_delta + DeviceDeltaCache must be
indistinguishable from the legacy assemble() dense build, cycle after cycle.

Two invariants:

1. *Outcome equality*: the same mutation feed driven through a legacy
   builder (assemble -> full upload -> schedule_round -> decode) and a slab
   builder (assemble_delta -> scatter apply -> schedule_round -> decode)
   yields identical RoundOutcomes every cycle -- scheduled map, preempted/
   rescheduled/failed sets, termination.

2. *Scatter == materialize*: after each delta apply, the device-resident
   problem is bit-identical to a fresh upload of bundle.materialize() --
   the scatter stream reproduces the ground truth exactly (no drift).

The scenario exercises submits, scheduling removals + leases, preemptions,
cancels mid-queue, reprioritisation, gang units (incl. a retry-banned
job), queue deletion, node removal, and a tight lookback that truncates a
queue (absent-slot handling).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, Queue, RunningJob
from armada_tpu.models import SchedulingProblem, decode_result, schedule_round
from armada_tpu.models.incremental import IncrementalBuilder
from armada_tpu.models.slab import DeviceDeltaCache


def make_config(lookback=100_000):
    return SchedulingConfig(
        shape_bucket=64,
        priority_classes={
            "low": PriorityClass("low", priority=100, preemptible=True),
            "high": PriorityClass("high", priority=1000, preemptible=False),
        },
        default_priority_class="high",
        max_queue_lookback=lookback,
        maximum_scheduling_burst=16,
    )


def make_world(cfg, rng, num_nodes=12, num_queues=3):
    F = cfg.resource_list_factory()
    nodes = [
        NodeSpec(
            id=f"n{i}",
            pool="default",
            total_resources=F.from_mapping({"cpu": "16", "memory": "64"}),
        )
        for i in range(num_nodes)
    ]
    queues = [Queue(f"q{i}", weight=1.0 + i) for i in range(num_queues)]
    return F, nodes, queues


def make_job(F, i, queue, pc="high", cpu=2, gang=None, sub=None):
    return JobSpec(
        id=f"j{i}",
        queue=queue,
        priority_class=pc,
        submit_time=float(i if sub is None else sub),
        resources=F.from_mapping({"cpu": str(cpu), "memory": "1"}),
        gang_id=gang or "",
        gang_cardinality=2 if gang else 0,
    )


class DualDriver:
    """Drives the same mutations through a legacy and a slab builder."""

    def __init__(self, cfg, queues, nodes):
        self.legacy = IncrementalBuilder(cfg, "default", queues)
        self.slab = IncrementalBuilder(cfg, "default", queues)
        for b in (self.legacy, self.slab):
            b.set_nodes(nodes)
        self.cache = DeviceDeltaCache()
        self.full_uploads = 0
        orig = self.cache._full_upload

        def counting(problem):
            self.full_uploads += 1
            return orig(problem)

        self.cache._full_upload = counting

    def each(self, fn):
        fn(self.legacy)
        fn(self.slab)

    def cycle(self, check_bits=True):
        problem, lctx = self.legacy.assemble()
        ldev = SchedulingProblem(*(jnp.asarray(a) for a in problem))
        lres = schedule_round(
            ldev,
            num_levels=len(lctx.ladder) + 2,
            max_slots=lctx.max_slots,
            slot_width=lctx.slot_width,
        )
        lout = decode_result(lres, lctx)

        bundle, sctx = self.slab.assemble_delta()
        sdev = self.cache.apply(bundle)
        if check_bits:
            truth = bundle.materialize()
            for name, dev_arr, host_arr in zip(sdev._fields, sdev, truth):
                np.testing.assert_array_equal(
                    np.asarray(dev_arr),
                    np.asarray(host_arr),
                    err_msg=f"scatter drift in field {name}",
                )
        sres = schedule_round(
            sdev,
            num_levels=len(sctx.ladder) + 2,
            max_slots=sctx.max_slots,
            slot_width=sctx.slot_width,
        )
        sout = decode_result(sres, sctx)

        assert sout.scheduled == lout.scheduled
        assert sorted(sout.preempted) == sorted(lout.preempted)
        assert sorted(sout.rescheduled) == sorted(lout.rescheduled)
        assert sorted(sout.failed) == sorted(lout.failed)
        assert sout.termination == lout.termination
        return lout


def apply_outcome(driver, out, spec_of, t):
    """Feed decisions back like the scheduler does."""
    leases = []
    for jid, nid in out.scheduled.items():
        spec = spec_of.get(jid)
        driver.each(lambda b: b.remove(jid))
        if spec is not None:
            leases.append(RunningJob(job=spec, node_id=nid))
    driver.each(lambda b: b.lease_many(leases))
    for jid in out.preempted:
        driver.each(lambda b: b.unlease(jid))


def test_slab_delta_matches_legacy_over_cycles():
    rng = np.random.default_rng(11)
    cfg = make_config()
    F, nodes, queues = make_world(cfg, rng)
    d = DualDriver(cfg, queues, nodes)
    spec_of = {}
    next_id = 0

    def submit(n, queue, pc="high", cpu=2, gang=None):
        nonlocal next_id
        out = []
        for _ in range(n):
            s = make_job(F, next_id, queue, pc=pc, cpu=cpu, gang=gang)
            spec_of[s.id] = s
            out.append(s)
            next_id += 1
        d.each(lambda b: b.submit_many(out))
        return out

    # preemptible background load hogging two nodes
    hogs = []
    for i in range(4):
        s = make_job(F, 10_000 + i, "q0", pc="low", cpu=8, sub=0)
        spec_of[s.id] = s
        hogs.append(s)
    d.each(lambda b: b.lease_many(
        [RunningJob(job=s, node_id=f"n{i // 2}") for i, s in enumerate(hogs)]
    ))

    submit(10, "q0")
    submit(8, "q1", cpu=3)
    submit(6, "q2", pc="low")
    out = d.cycle()
    apply_outcome(d, out, spec_of, 1)

    # gang unit + a retry-banned single (slow path)
    gang_jobs = submit(2, "q1", gang="gang-a")
    banned = make_job(F, 20_000, "q2", cpu=2)
    spec_of[banned.id] = banned
    d.each(lambda b: b.submit(banned, banned_nodes=["n0", "n1"]))
    out = d.cycle()
    apply_outcome(d, out, spec_of, 2)

    # churn: cancels mid-queue, reprioritisation, more submits
    victims = [jid for jid in list(spec_of) if jid.startswith("j")][:3]
    for jid in victims:
        d.each(lambda b: b.remove(jid))
        spec_of.pop(jid, None)
    repri = next(iter([s for s in spec_of.values() if s.queue == "q1"]), None)
    if repri is not None:
        bumped = JobSpec(
            id=repri.id, queue=repri.queue, priority_class=repri.priority_class,
            submit_time=repri.submit_time, resources=repri.resources,
            priority=50,
        )
        spec_of[bumped.id] = bumped
        d.each(lambda b: b.reprioritise(bumped))
    submit(5, "q2")
    out = d.cycle()
    apply_outcome(d, out, spec_of, 3)

    # queue deletion + node removal
    d.each(lambda b: b.set_queues([Queue("q0", weight=1.0), Queue("q1", weight=2.0)]))
    d.each(lambda b: b.set_nodes(
        [n for n in nodes if n.id != "n3"]
    ))
    out = d.cycle()
    apply_outcome(d, out, spec_of, 4)

    # restore + more cycles
    d.each(lambda b: b.set_queues(queues))
    d.each(lambda b: b.set_nodes(nodes))
    submit(6, "q2", pc="low", cpu=1)
    for t in range(5, 8):
        out = d.cycle()
        apply_outcome(d, out, spec_of, t)

    # The delta path must actually be exercised: full uploads only on shape
    # or epoch changes (first cycle + slab growths + node epoch bumps), not
    # every cycle.
    assert d.full_uploads < 7, f"delta path never engaged ({d.full_uploads} full uploads)"
    # ... and steady-state cycles must carry the candidate order as a gq
    # SPLICE (device-side rebuild), not a 4MB re-upload.
    assert getattr(d.cache, "splice_applies", 0) > 0, "gq splice never engaged"


def test_slab_delta_lookback_truncation():
    """A queue longer than the lookback: beyond-lookback jobs become absent
    slots (not failed), and re-enter exactly when the queue drains."""
    cfg = make_config(lookback=6)
    rng = np.random.default_rng(5)
    F, nodes, queues = make_world(cfg, rng, num_nodes=4, num_queues=2)
    d = DualDriver(cfg, queues, nodes)
    spec_of = {}
    jobs = []
    for i in range(14):
        s = make_job(F, i, "q0", cpu=4)
        spec_of[s.id] = s
        jobs.append(s)
    d.each(lambda b: b.submit_many(jobs))
    for t in range(4):
        out = d.cycle()
        # beyond-lookback jobs must never be reported failed
        assert not list(out.failed)
        apply_outcome(d, out, spec_of, t)


def test_bundle_seq_gap_forces_full_upload():
    cfg = make_config()
    rng = np.random.default_rng(7)
    F, nodes, queues = make_world(cfg, rng)
    b = IncrementalBuilder(cfg, "default", queues)
    b.set_nodes(nodes)
    b.submit_many([make_job(F, i, "q0") for i in range(5)])
    cache = DeviceDeltaCache()
    bundle, _ = b.assemble_delta()
    cache.apply(bundle)
    skipped, _ = b.assemble_delta()  # never applied
    b.submit_many([make_job(F, 100, "q1")])
    bundle3, ctx3 = b.assemble_delta()
    dev = cache.apply(bundle3)
    truth = bundle3.materialize()
    for name, dev_arr, host_arr in zip(dev._fields, dev, truth):
        np.testing.assert_array_equal(
            np.asarray(dev_arr), np.asarray(host_arr), err_msg=name
        )


def test_slab_delta_market_pool():
    """Market pools ride the slab path: candidate order is the per-cycle
    price permutation (incremental._market_perm), per-slot prices are
    scattered with the dirty rows, and a price-table MOVE bumps the bundle
    sig's price epoch so exactly one full upload re-prices every slot."""
    import dataclasses
    from armada_tpu.core.config import PoolConfig

    cfg = dataclasses.replace(
        make_config(),
        pools=(PoolConfig("default", market_driven=True, spot_price_cutoff=0.5),),
    )
    rng = np.random.default_rng(23)
    F, nodes, queues = make_world(cfg, rng)
    prices = {}

    def price_of(job):
        return prices.get((job.queue, job.price_band), 0.0)

    d = DualDriver(cfg, queues, nodes)
    d.each(lambda b: setattr(b, "bid_price_of", price_of))
    bands = ("", "low", "high")
    for q in queues:
        for band in bands:
            prices[(q.name, band)] = float(rng.integers(1, 8))
    spec_of = {}
    next_id = [0]

    def submit(n, queue, band, pc="high", cpu=2, gang=None):
        batch = []
        for _ in range(n):
            s = dataclasses.replace(
                make_job(F, next_id[0], queue, pc=pc, cpu=cpu, gang=gang),
                price_band=band,
            )
            spec_of[s.id] = s
            batch.append(s)
            next_id[0] += 1
        d.each(lambda b: b.submit_many(batch))

    # preemptible running load in mixed bands: evictee market order
    hogs = []
    for i in range(4):
        s = dataclasses.replace(
            make_job(F, 10_000 + i, "q0", pc="low", cpu=8, sub=0),
            price_band=bands[i % 3],
        )
        spec_of[s.id] = s
        hogs.append(s)
    d.each(
        lambda b: b.lease_many(
            [RunningJob(job=s, node_id=f"n{i // 2}") for i, s in enumerate(hogs)]
        )
    )
    submit(8, "q0", "low")
    submit(8, "q1", "high", cpu=3)
    submit(6, "q2", "", pc="low")
    out = d.cycle()
    apply_outcome(d, out, spec_of, 1)

    # gang unit (market virtual rank) + steady prices: deltas engage
    submit(2, "q1", "low", gang="gang-m")
    submit(4, "q1", "low")
    out = d.cycle()
    apply_outcome(d, out, spec_of, 2)
    uploads_before_move = d.full_uploads

    # price move: q1 bands TIE exactly (sub, id merge) and q0 reorders
    prices[("q1", "low")] = prices[("q1", "high")] = 6.0
    prices[("q0", "low")] = 7.0
    out = d.cycle()
    apply_outcome(d, out, spec_of, 3)
    assert d.full_uploads == uploads_before_move + 1

    # prices unchanged again: back to O(deltas) scatters
    submit(3, "q2", "high")
    out = d.cycle()
    apply_outcome(d, out, spec_of, 4)
    assert d.full_uploads == uploads_before_move + 1


def test_ctx_id_snapshots_survive_post_assemble_mutations():
    """HostContext id vectors are copy-on-write: a slot reused by a remove +
    resubmit AFTER assemble_delta must not corrupt the outstanding context's
    ids (the overlapped decode reads them after the next cycle's submits)."""
    cfg = make_config()
    F = cfg.resource_list_factory()
    b = IncrementalBuilder(cfg, "default", [Queue("q")])
    b.set_nodes(
        [NodeSpec(id="n0", pool="default",
                  total_resources=F.from_mapping({"cpu": 8, "memory": 32}))]
    )
    spec = JobSpec(id="old-job", queue="q",
                   resources=F.from_mapping({"cpu": 1, "memory": 1}))
    b.submit(spec)
    bundle, ctx = b.assemble_delta()
    bundle.materialize()
    slot = int(b.jobs.slot[b.jobs._locate(b"old-job")])
    assert ctx.gang_ids_vec[slot] == b"old-job"
    # reuse the slot: remove then submit a different job
    b.remove("old-job")
    b.submit(JobSpec(id="new-job", queue="q",
                     resources=F.from_mapping({"cpu": 1, "memory": 1})))
    assert b.jobs.slot[b.jobs._locate(b"new-job")] == slot  # slot reused
    # the outstanding ctx still decodes the OLD id
    assert ctx.gang_ids_vec[slot] == b"old-job"
    # runs-table ids likewise
    b.lease(RunningJob(job=JobSpec(
        id="r0", queue="q", resources=F.from_mapping({"cpu": 1, "memory": 1})),
        node_id="n0"))
    bundle2, ctx2 = b.assemble_delta()
    bundle2.materialize()
    rslot = int(b.runs.slot[b.runs._locate(b"r0")])
    assert ctx2.run_ids_vec[rslot] == b"r0"
    b.unlease("r0")
    b.lease(RunningJob(job=JobSpec(
        id="r1", queue="q", resources=F.from_mapping({"cpu": 1, "memory": 1})),
        node_id="n0"))
    assert ctx2.run_ids_vec[rslot] == b"r0"


def test_running_gang_cascade_on_slab_path():
    """The partial-preemption cascade (run_round_on_device running-gang
    fate-sharing) works off the SLAB context's running_gangs mapping: slot
    indices, not table positions."""
    from armada_tpu.models import run_round_on_device

    cfg = make_config()
    F, nodes, queues = make_world(cfg, None, num_nodes=2, num_queues=2)
    # two full-node gang members running; a non-preemptible high job wants
    # one node
    driver = DualDriver(cfg, queues, nodes)
    members = [
        make_job(F, i, "q0", pc="low", cpu=16, gang="g1", sub=-1.0)
        for i in range(2)
    ]
    leases = [RunningJob(job=m, node_id=f"n{i}") for i, m in enumerate(members)]
    driver.each(lambda b: b.lease_many(leases))
    driver.each(lambda b: [b.note_running_gang("q0", "g1", m.id) for m in members])
    intruder = make_job(F, 9, "q1", pc="high", cpu=16)
    driver.each(lambda b: b.submit(intruder))

    problem, lctx = driver.legacy.assemble()
    _, lout = run_round_on_device(problem, lctx, cfg)
    bundle, sctx = driver.slab.assemble_delta()
    assert sctx.running_gangs, "slab ctx lost the running-gang groups"
    _, sout = run_round_on_device(
        bundle.stats_view(), sctx, cfg, device_problem=driver.cache.apply(bundle)
    )
    for out in (lout, sout):
        assert sorted(out.preempted) == ["j0", "j1"], out.preempted
        assert "j9" in out.scheduled
    assert sout.scheduled == lout.scheduled
