"""Interprocedural dataflow for armada-lint v3: def-use + provenance.

The costliest hard-won constraints in CLAUDE.md are *semantic*, not
syntactic -- "nothing computed in the while-loop body from a gathered row"
(a 6x regression), "big arrays must not thread through cond/switch branch
returns", "jit programs scattering into sharded slabs must pin
out_shardings" (round 12's silent slab gather).  A per-node AST matcher
cannot express "is this value derived from X"; this module can, cheaply:

* a per-function CFG (basic blocks over the statement list, with loop
  back-edges, branch joins and try-handler edges);
* a forward fixpoint over a small provenance lattice -- each value carries
  a set of tags, joined by union at control-flow merges;
* memoized multi-hop call summaries (the callee is analyzed once per
  distinct argument-tag signature; summary chains are bounded by a hop
  budget, ``_MAX_SUMMARY_HOPS``, and cycles fall back to the generic
  transfer via the in-progress guard -- so analysis cost is bounded by
  construction, not by the call graph's depth);
* a package-wide module registry (``project_module``) resolving
  ``import``/``from ... import`` targets inside the repository so
  summaries survive MODULE boundaries; each consulted module is keyed by
  its content hash and recorded as a dependency (``dep_hashes``) so the
  CLI's ``--cache`` can invalidate soundly;
* field-sensitive ``self.*`` (and any dotted-chain) attribute provenance:
  a bound field reads back its assigned tags flow-sensitively within a
  function, and cross-method per-class field maps (``class_field_tags``)
  answer reads of fields some OTHER method of the class assigned;
* container-element flow: ``lst.append(v)`` / ``extend`` / ``update`` et
  al. merge the value's tags into the receiver binding, so a "list of
  finish closures" built in a loop and consumed later carries the
  closures' provenance (the exact shape that defeated the v2 def-use);
* resolution of jax higher-order callables: `lax.while_loop`/`fori_loop`
  bodies, `lax.cond`/`switch` branches and `jax.jit`-traced functions are
  resolved through local def-use (including the repo's `body =
  make_body(...)` idiom, via the helper's returned inner def).

Tags (the lattice is the powerset of these, ordered by inclusion):

``gather``   read through a dynamically-indexed gather (``x[i]`` with a
             traced index, ``jnp.take``, ``dynamic_slice``).  KILLED by
             reductions (``sum``/``min``/``argmin``/...) -- an argmin
             *result* is a fresh scalar, not a gathered row.
``carry``    derived from the analyzed function's own parameters (the loop
             carry, or a jit-traced function's operands).
``ext``      derived from the closure/module environment -- loop-INVARIANT
             from the body's point of view.
``whole``    whole-buffer provenance: the value IS one of the big input
             buffers (a carry field, a closure table), possibly scattered
             into.  Preserved only by shape-preserving whole-buffer ops
             (``.at[...].set/add``, ``jnp.where``/``select``, ``astype``,
             ``reshape``, broadcast subscripts ``[:, None]``); killed by
             element arithmetic, reductions, and real subscripts -- so a
             freshly computed [N] row is NOT whole, which is exactly the
             sanctioned "pass rows out of the switch" idiom.
``py``       trace-time python static (shapes, ``range`` counts, constants).
             A gathered scalar times a static int is index arithmetic, not
             a hoisting hazard; rules use this to tell tables from shapes.
``shard``    mesh-sharded provenance: the value flowed through an explicit
             placement (``jax.device_put(x, sharding)``), a sharding
             constructor (``NamedSharding``/``PositionalSharding``) or the
             repo's sharding factories (``problem_shardings``/
             ``shard_problem``).  Sticky through arithmetic, selects,
             scatters and generic calls -- a derived view of a sharded
             slab is still sharded; the unpinned-out-shardings rule keys
             on it.
``reduced``  produced by an ASSOCIATION-SENSITIVE reduction (``jnp.sum``,
             ``cumsum``, ``mean``, ``dot``/``matmul``/``einsum``, the
             segment sums): XLA may tree-reduce these, so their f32 result
             depends on grouping.  Sticky through arithmetic and generic
             calls; NOT set by association-exact reductions
             (``min``/``max``/``argmin``/``any``/``all``).  The
             vectorized-accumulator-ordering rule keys on it (the r15
             "sequential f32 association" constraint).

Approximations are deliberate and documented where they matter: scatter
results carry the BASE buffer's provenance (the scattered value does not
taint the buffer -- rules inspect scatter sites directly), attribute reads
of UNASSIGNED fields inherit the object's tags, unknown calls union their
argument tags minus ``whole``/``py``, container-element tags merge into
the container (per-element precision is not kept), and cross-method class
field maps are flow-INSENSITIVE unions built after the module pass (the
module pass itself sees only the flow-sensitive local bindings).  Rules
built on the engine trade completeness for zero-dependency stdlib-``ast``
speed, and every rule is pinned by a true-positive + syntactic-twin
fixture so lattice regressions fail in tests/test_dataflow.py or
tests/test_lint.py, not in review.
"""

from __future__ import annotations

import ast
import hashlib
import os
from typing import Iterable, Optional

GATHER = "gather"
CARRY = "carry"
EXT = "ext"
WHOLE = "whole"
PY = "py"
SHARD = "shard"
REDUCED = "reduced"

EMPTY: frozenset = frozenset()
_ARRAYISH = frozenset({GATHER, CARRY, EXT, WHOLE, SHARD, REDUCED})

# Bounded work: fixpoint passes per function, nested-def depth, and the
# summary-chain hop budget (cycles bail to generic via the in-progress
# guard well before the cap matters).
_MAX_PASSES = 40
_MAX_DEPTH = 6
_MAX_SUMMARY_HOPS = 3


def dotted(node: ast.AST) -> str:
    """`a.b.c` for Attribute/Name chains, '' for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def at_scatter(call: ast.Call):
    """(base_expr, index_expr, method) when `call` is
    `<base>.at[<index>].<method>(...)`, else None."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    sub = f.value
    if not (
        isinstance(sub, ast.Subscript)
        and isinstance(sub.value, ast.Attribute)
        and sub.value.attr == "at"
    ):
        return None
    return sub.value.value, sub.slice, f.attr


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


# Call classification by final name component (jnp.sum, x.sum, np.sum all
# behave the same for provenance purposes).
_REDUCERS = {
    "sum", "min", "max", "argmin", "argmax", "any", "all", "mean", "prod",
    "nonzero", "count_nonzero", "segment_min", "segment_max", "segment_sum",
}
# Association-SENSITIVE reductions: XLA may tree-reduce them, so the f32
# result depends on grouping.  min/max/argmin/any/all are association-exact
# and deliberately absent.  cumsum/cumprod are shape-preserving (not in
# _REDUCERS) but every element is a grouped partial reduction.
_ASSOC_REDUCERS = {
    "sum", "mean", "prod", "dot", "matmul", "einsum", "tensordot", "vdot",
    "segment_sum",
}
_CUMULATIVE = {"cumsum", "cumulative_sum", "cumprod"}
# Container mutators: the value's tags merge into the receiver binding
# (list-of-closures flow; per-element precision is not kept).
_CONTAINER_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault", "appendleft",
}
_WHERE_LIKE = {"where", "select"}
_WHOLE_PRESERVING = {"astype", "reshape", "copy"}
_GATHER_ADDERS = {"take", "take_along_axis", "dynamic_slice", "dynamic_slice_in_dim"}
# Sharding constructors/factories: results carry SHARD.  `device_put` adds
# it only when an explicit placement argument is visible at the call.
_SHARD_MAKERS = {
    "NamedSharding", "PositionalSharding", "problem_shardings", "shard_problem",
}
_PY_KEEPERS = {"range", "len", "reversed", "enumerate", "int", "bool", "abs"}
_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype"}

_LOOP_CALLS = {
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
}
_BRANCH_CALLS = {"jax.lax.cond", "lax.cond", "jax.lax.switch", "lax.switch"}


# --------------------------------------------------------------------------
# CFG
# --------------------------------------------------------------------------

class _CFG:
    """Basic blocks of statements + successor edges.  Block 0 is entry;
    the virtual exit has no block (returns record into the analysis)."""

    def __init__(self) -> None:
        self.blocks: list[list[ast.stmt]] = []
        self.succ: list[set[int]] = []

    def new(self) -> int:
        self.blocks.append([])
        self.succ.append(set())
        return len(self.blocks) - 1

    def edge(self, a: int, b: int) -> None:
        self.succ[a].add(b)


def _build_cfg(body: list[ast.stmt]) -> _CFG:
    cfg = _CFG()
    entry = cfg.new()

    # (header_block, after_block) per enclosing loop, for continue/break.
    loop_stack: list[tuple[int, int]] = []

    def emit(stmts: list[ast.stmt], cur: int) -> int:
        """Append stmts starting at block `cur`; return the live exit block
        (a fresh empty block when flow falls through)."""
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                cfg.blocks[cur].append(stmt)  # evaluates the test
                then_b = cfg.new()
                cfg.edge(cur, then_b)
                then_end = emit(stmt.body, then_b)
                join = cfg.new()
                cfg.edge(then_end, join)
                if stmt.orelse:
                    else_b = cfg.new()
                    cfg.edge(cur, else_b)
                    cfg.edge(emit(stmt.orelse, else_b), join)
                else:
                    cfg.edge(cur, join)
                cur = join
            elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                header = cfg.new()
                cfg.edge(cur, header)
                cfg.blocks[header].append(stmt)  # test / target binding
                after = cfg.new()
                body_b = cfg.new()
                cfg.edge(header, body_b)
                cfg.edge(header, after)
                loop_stack.append((header, after))
                body_end = emit(stmt.body, body_b)
                loop_stack.pop()
                cfg.edge(body_end, header)  # back edge
                if stmt.orelse:
                    else_b = cfg.new()
                    cfg.edge(header, else_b)
                    cfg.edge(emit(stmt.orelse, else_b), after)
                cur = after
            elif isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
                body_b = cfg.new()
                cfg.edge(cur, body_b)
                body_end = emit(stmt.body, body_b)
                join = cfg.new()
                for handler in stmt.handlers:
                    h_b = cfg.new()
                    # an exception may fire anywhere in the body: edge from
                    # both the entry and the exit of the protected region
                    cfg.edge(body_b, h_b)
                    cfg.edge(body_end, h_b)
                    cfg.edge(emit(handler.body, h_b), join)
                if stmt.orelse:
                    else_b = cfg.new()
                    cfg.edge(body_end, else_b)
                    cfg.edge(emit(stmt.orelse, else_b), join)
                else:
                    cfg.edge(body_end, join)
                if stmt.finalbody:
                    fin_b = cfg.new()
                    cfg.edge(join, fin_b)
                    join = emit(stmt.finalbody, fin_b)
                cur = join
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                cfg.blocks[cur].append(stmt)  # evaluates context exprs
                cur = emit(stmt.body, cur)
            elif isinstance(stmt, (ast.Break, ast.Continue)):
                if loop_stack:
                    header, after = loop_stack[-1]
                    cfg.edge(cur, after if isinstance(stmt, ast.Break) else header)
                cur = cfg.new()  # dead fallthrough
            elif isinstance(stmt, (ast.Return, ast.Raise)):
                cfg.blocks[cur].append(stmt)
                cur = cfg.new()  # dead fallthrough
            else:
                cfg.blocks[cur].append(stmt)
        return cur

    emit(body, entry)
    return cfg


# --------------------------------------------------------------------------
# analysis records
# --------------------------------------------------------------------------

class ScatterSite:
    """One `<base>.at[<index>].<method>(<value>)` occurrence."""

    __slots__ = ("call", "base", "index", "method", "base_tags", "index_tags", "value_tags")

    def __init__(self, call, base, index, method, base_tags, index_tags, value_tags):
        self.call = call
        self.base = base
        self.index = index
        self.method = method
        self.base_tags = base_tags
        self.index_tags = index_tags
        self.value_tags = value_tags


class BranchSite:
    """One lax.cond/lax.switch call with its resolved branch analyses."""

    __slots__ = ("call", "branches")

    def __init__(self, call, branches):
        self.call = call
        self.branches = branches  # list[FunctionAnalysis]


class LoopSite:
    """One lax.while_loop/fori_loop call with its resolved body analyses."""

    __slots__ = ("call", "bodies")

    def __init__(self, call, bodies):
        self.call = call
        self.bodies = bodies  # list[FunctionAnalysis]


class JitSite:
    """One jax.jit application (decorator or direct call).

    `out_shardings`: True (kwarg present), False (definitely absent), or
    None (a ``**kwargs`` splat hides the call signature statically)."""

    __slots__ = ("node", "fn", "analysis", "out_shardings")

    def __init__(self, node, fn, analysis, out_shardings):
        self.node = node
        self.fn = fn
        self.analysis = analysis
        self.out_shardings = out_shardings


# --------------------------------------------------------------------------
# per-function analysis
# --------------------------------------------------------------------------

class FunctionAnalysis:
    """CFG + fixpoint + annotation for one function (or module) body.

    After construction: `tags(node)` answers provenance for any expression
    node in this function or its nested defs; `scatters`, `branch_sites`
    and `returns` hold the recorded sites; `exit_env` is the name->tags
    environment at function exit (tests pin the lattice through it)."""

    def __init__(
        self,
        ma: "ModuleAnalysis",
        fn,  # ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda | ast.Module
        seeds: Optional[dict] = None,
        closure: Optional[dict] = None,
        depth: int = 0,
        hops: int = 0,
    ):
        self.ma = ma
        self.fn = fn
        self.depth = depth
        self.hops = hops  # summary-chain position: gates further summaries
        self.closure = dict(closure or {})
        self.node_tags: dict[int, frozenset] = {}
        self.scatters: list[ScatterSite] = []
        self.branch_sites: list[BranchSite] = []
        self.returns: list[tuple[ast.AST, Optional[ast.AST], frozenset]] = []
        self.return_tags: frozenset = EMPTY
        self.children: dict[int, "FunctionAnalysis"] = {}
        self.def_site_env: dict[int, dict] = {}
        self._local_defs: dict[str, list] = {}

        if isinstance(fn, ast.Module):
            body = fn.body
            params: list[str] = []
        elif isinstance(fn, ast.Lambda):
            ret = ast.Return(value=fn.body)
            ast.copy_location(ret, fn.body)
            body = [ret]
            params = [a.arg for a in _all_args(fn.args)]
        else:
            body = fn.body
            params = [a.arg for a in _all_args(fn.args)]

        self._collect_local_defs(body)
        init_env: dict[str, frozenset] = {}
        seeds = seeds or {}
        for p in params:
            init_env[p] = frozenset(seeds.get(p, {CARRY, WHOLE}))
        self._run(body, init_env)

    # ----------------------------------------------------------- queries ---

    def tags(self, node: ast.AST) -> frozenset:
        t = self.node_tags.get(id(node))
        if t is not None:
            return t
        for child in self.children.values():
            t = child.tags(node)
            if t:
                return t
        return EMPTY

    def tree(self) -> Iterable["FunctionAnalysis"]:
        """This analysis + every nested-def analysis, depth-first."""
        yield self
        for child in self.children.values():
            yield from child.tree()

    def all_scatters(self) -> Iterable[ScatterSite]:
        for fa in self.tree():
            yield from fa.scatters

    def all_branch_sites(self) -> Iterable[BranchSite]:
        for fa in self.tree():
            yield from fa.branch_sites

    def name_tags(self, name: str) -> frozenset:
        return self.exit_env.get(name, EMPTY)

    # ----------------------------------------------------- def resolution ---

    def _collect_local_defs(self, body: list[ast.stmt]) -> None:
        """Name -> candidate def nodes / aliases, flow-insensitively, for
        resolving callables passed to jax control-flow primitives."""

        def scan(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._local_defs.setdefault(stmt.name, []).append(stmt)
                    continue  # do not descend into nested scopes
                if isinstance(stmt, ast.Assign):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            self._local_defs.setdefault(tgt.id, []).append(stmt.value)
                # descend into compound-statement bodies only (same scope)
                if isinstance(
                    stmt,
                    (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With, ast.AsyncWith, ast.Try),
                ):
                    for field in ("body", "orelse", "finalbody"):
                        sub = getattr(stmt, field, None)
                        if sub:
                            scan(sub)
                    for handler in getattr(stmt, "handlers", []):
                        scan(handler.body)

        scan(body)

    def resolve_callables(self, expr: ast.AST, _seen=None) -> list[tuple[ast.AST, "FunctionAnalysis | None"]]:
        """Candidate (def node, defining analysis) pairs for a callable
        expression: a direct def/lambda, a Name bound to one, or a Name
        bound to a call of a module-local factory (one hop through its
        `return <inner def>`)."""
        if _seen is None:
            _seen = set()
        out: list[tuple[ast.AST, Optional[FunctionAnalysis]]] = []
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return [(expr, self)]
        if isinstance(expr, ast.Name):
            if expr.id in _seen:
                return out
            _seen.add(expr.id)
            for cand in self._local_defs.get(expr.id, []):
                if isinstance(cand, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append((cand, self))
                elif isinstance(cand, ast.Name):
                    out.extend(self.resolve_callables(cand, _seen))
                elif isinstance(cand, ast.Call):
                    out.extend(self._resolve_factory(cand))
            if not out and self.ma.parent_of(self) is not None:
                out.extend(self.ma.parent_of(self).resolve_callables(expr, _seen))
            if not out:
                mod_def = self.ma.module_defs.get(expr.id)
                if mod_def is not None:
                    out.append((mod_def, self.ma.module_fa))
        return out

    def resolve_callable_list(self, expr: ast.AST) -> list[tuple[ast.AST, "FunctionAnalysis | None"]]:
        """For lax.switch's branch list: a literal [f, g, ...] or a Name
        bound to one."""
        exprs: list[ast.AST] = []
        if isinstance(expr, (ast.List, ast.Tuple)):
            exprs = list(expr.elts)
        elif isinstance(expr, ast.Name):
            for cand in self._local_defs.get(expr.id, []):
                if isinstance(cand, (ast.List, ast.Tuple)):
                    exprs.extend(cand.elts)
        out = []
        for e in exprs:
            out.extend(self.resolve_callables(e))
        return out

    def _resolve_factory(self, call: ast.Call):
        """`body = make_body(...)` -> make_body's `return <inner def>`."""
        fname = dotted(call.func)
        target = self.ma.module_defs.get(fname)
        if target is None:
            return []
        factory_fa = self.ma.function_analysis(target)
        out = []
        for ret_node, value, _tags in factory_fa.returns:
            if isinstance(value, ast.Name):
                for cand, fa in factory_fa.resolve_callables(value):
                    out.append((cand, fa))
            elif isinstance(value, (ast.FunctionDef, ast.Lambda)):
                out.append((value, factory_fa))
        return out

    # ---------------------------------------------------------- fixpoint ---

    def _run(self, body: list[ast.stmt], init_env: dict) -> None:
        cfg = _build_cfg(body)
        n = len(cfg.blocks)
        in_env: list[Optional[dict]] = [None] * n
        in_env[0] = dict(init_env)
        work = [0]
        passes = 0
        while work and passes < _MAX_PASSES * n:
            passes += 1
            b = work.pop()
            env = dict(in_env[b] or {})
            for stmt in cfg.blocks[b]:
                self._exec(stmt, env, record=False)
            for s in cfg.succ[b]:
                merged = _join(in_env[s], env)
                if merged is not None:
                    in_env[s] = merged
                    if s not in work:
                        work.append(s)
        # annotation pass: record node tags + sites with converged envs
        exit_env: dict[str, frozenset] = {}
        for b in range(n):
            env = dict(in_env[b] or {})
            for stmt in cfg.blocks[b]:
                self._exec(stmt, env, record=True)
            if not cfg.succ[b]:
                _join_into(exit_env, env)
        self.exit_env = exit_env
        self.return_tags = frozenset().union(*(t for _, _, t in self.returns)) if self.returns else EMPTY

    # ------------------------------------------------------- statements ----

    def _exec(self, stmt: ast.stmt, env: dict, record: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            env[stmt.name] = EMPTY
            if record and self.depth < _MAX_DEPTH:
                self._child(stmt, env)
            return
        if isinstance(stmt, ast.ClassDef):
            env[stmt.name] = EMPTY
            if record and self.depth < _MAX_DEPTH:
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.ma.note_method(stmt.name, sub)
                        self._child(sub, env)
            return
        if isinstance(stmt, ast.Assign):
            val = self._eval(stmt.value, env, record)
            for tgt in stmt.targets:
                self._bind(tgt, val, env, record)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                val = self._eval(stmt.value, env, record)
                self._bind(stmt.target, val, env, record)
            return
        if isinstance(stmt, ast.AugAssign):
            val = self._eval(stmt.value, env, record)
            if isinstance(stmt.target, ast.Name):
                old = env.get(stmt.target.id, EMPTY)
                env[stmt.target.id] = _arith(old | val)
            else:
                self._bind(stmt.target, val, env, record)
            return
        if isinstance(stmt, ast.Return):
            t = self._eval(stmt.value, env, record) if stmt.value is not None else EMPTY
            if record:
                self.returns.append((stmt, stmt.value, t))
            return
        if isinstance(stmt, (ast.While, ast.If)):
            self._eval(stmt.test, env, record)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._bind(stmt.target, self._iter_tags(stmt.iter, env, record), env, record)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                t = self._eval(item.context_expr, env, record)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, t, env, record)
            return
        if isinstance(stmt, ast.Expr):
            self._eval(stmt.value, env, record)
            return
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._eval(child, env, record)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    env.pop(tgt.id, None)
            return
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                name = (alias.asname or alias.name).split(".")[0]
                env[name] = EMPTY  # modules/callables carry no provenance
            return
        # Global/Nonlocal/Pass: no provenance effect.

    def _child(self, fn, env: dict) -> None:
        """Eagerly analyze a nested def in the env at its def site; these
        children answer tags() for nodes inside nested scopes (cond/switch
        branches, helper closures) under THIS analysis's seeds."""
        self.def_site_env[id(fn)] = dict(env)
        if id(fn) not in self.children:
            self.children[id(fn)] = FunctionAnalysis(
                self.ma, fn,
                seeds={a.arg: frozenset({EXT, WHOLE}) for a in _all_args(fn.args)},
                closure=_closure_of(env, self.closure),
                depth=self.depth + 1,
                hops=self.hops,
            )
            self.ma._register(self.children[id(fn)], self)

    def _iter_tags(self, it: ast.AST, env: dict, record: bool) -> frozenset:
        t = self._eval(it, env, record)
        if isinstance(it, ast.Call) and _last(dotted(it.func)) in _PY_KEEPERS:
            return frozenset({PY})
        if isinstance(it, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) for e in it.elts
        ):
            return frozenset({PY})
        return t - {WHOLE}  # iterating a buffer yields rows, not the buffer

    def _bind(self, tgt: ast.AST, val: frozenset, env: dict, record: bool) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = val
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._bind(e, val, env, record)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, val, env, record)
        elif isinstance(tgt, (ast.Subscript, ast.Attribute)):
            # a store into a container/attribute merges provenance into the
            # root name (def-use continues through the mutated object); an
            # Attribute-chain target ALSO binds its dotted key so later
            # reads of exactly that field are flow-sensitively precise
            # (field-sensitive self.* provenance)
            if isinstance(tgt, ast.Subscript):
                self._eval(tgt.slice, env, record)
            elif isinstance(tgt, ast.Attribute):
                d = dotted(tgt)
                if d:
                    env[d] = val
            root = tgt
            while isinstance(root, (ast.Subscript, ast.Attribute)):
                root = root.value
            if isinstance(root, ast.Name):
                env[root.id] = env.get(root.id, EMPTY) | (val - {WHOLE})

    # ------------------------------------------------------- expressions ---

    def _eval(self, node: ast.AST, env: dict, record: bool) -> frozenset:
        t = self._eval_inner(node, env, record)
        if record:
            self.node_tags[id(node)] = t
        return t

    def _eval_inner(self, node: ast.AST, env: dict, record: bool) -> frozenset:
        if isinstance(node, ast.Constant):
            return frozenset({PY})
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            if node.id in self.closure:
                return self.closure[node.id]
            if node.id in self.ma.module_env:
                return self.ma.module_env[node.id]
            return frozenset({EXT})
        if isinstance(node, ast.Attribute):
            base = self._eval(node.value, env, record)
            if node.attr in _SHAPE_ATTRS:
                return frozenset({PY})
            d = dotted(node)
            if d:
                # flow-sensitive field binding from this function
                if d in env:
                    return env[d]
                if d in self.closure:
                    return self.closure[d]
            # cross-method class field map: a field some OTHER method of
            # this class assigned (built after the module pass)
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                cls = self.ma.method_class(self.fn)
                if cls is not None:
                    ft = self.ma.class_field_tags(cls).get(node.attr)
                    if ft:
                        return ft
            return base
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node, env, record)
        if isinstance(node, ast.BinOp):
            left = self._eval(node.left, env, record)
            right = self._eval(node.right, env, record)
            return _arith(left | right)
        if isinstance(node, ast.BoolOp):
            u = frozenset().union(*(self._eval(v, env, record) for v in node.values))
            return _arith(u)
        if isinstance(node, ast.Compare):
            u = self._eval(node.left, env, record)
            for c in node.comparators:
                u = u | self._eval(c, env, record)
            return _arith(u)
        if isinstance(node, ast.UnaryOp):
            return _arith(self._eval(node.operand, env, record))
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env, record)
            # like jnp.where: a whole-buffer pick stays whole
            return self._eval(node.body, env, record) | self._eval(node.orelse, env, record)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, record)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            if not node.elts:
                return EMPTY
            return frozenset().union(*(self._eval(e, env, record) for e in node.elts))
        if isinstance(node, ast.Dict):
            parts = [self._eval(v, env, record) for v in node.values if v is not None]
            for k in node.keys:
                if k is not None:
                    self._eval(k, env, record)
            return frozenset().union(*parts) if parts else EMPTY
        if isinstance(node, ast.NamedExpr):  # walrus: binds AND yields
            val = self._eval(node.value, env, record)
            self._bind(node.target, val, env, record)
            return val
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env, record)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            inner = dict(env)
            for gen in node.generators:
                self._bind(gen.target, self._iter_tags(gen.iter, inner, record), inner, record)
                for cond in gen.ifs:
                    self._eval(cond, inner, record)
            if isinstance(node, ast.DictComp):
                self._eval(node.key, inner, record)
                return self._eval(node.value, inner, record)
            return self._eval(node.elt, inner, record)
        if isinstance(node, ast.Lambda):
            return EMPTY
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                self._eval(v, env, record)
            return frozenset({PY})
        if isinstance(node, ast.FormattedValue):
            return self._eval(node.value, env, record)
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self._eval(part, env, record)
            return EMPTY
        # conservative default
        u = EMPTY
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                u = u | self._eval(child, env, record)
        return u

    def _index_parts(self, index: ast.AST) -> list[ast.AST]:
        return list(index.elts) if isinstance(index, ast.Tuple) else [index]

    def _index_static(self, part: ast.AST, env: dict) -> bool:
        """A trace-time-static index component: constants, python-static
        names/arithmetic, or slices of those."""
        if isinstance(part, ast.Constant):
            return True
        if isinstance(part, ast.Slice):
            return all(
                p is None or self._index_static(p, env)
                for p in (part.lower, part.upper, part.step)
            )
        if isinstance(part, ast.UnaryOp):
            return self._index_static(part.operand, env)
        if isinstance(part, ast.BinOp):
            return self._index_static(part.left, env) and self._index_static(part.right, env)
        if isinstance(part, ast.Name):
            return PY in env.get(part.id, self.closure.get(part.id, EMPTY))
        return False

    def _index_broadcast(self, part: ast.AST) -> bool:
        """A pure broadcast component (`:` or None) -- keeps WHOLE."""
        if isinstance(part, ast.Slice):
            return part.lower is None and part.upper is None and part.step is None
        return isinstance(part, ast.Constant) and part.value is None

    def _eval_subscript(self, node: ast.Subscript, env: dict, record: bool) -> frozenset:
        base = self._eval(node.value, env, record)
        self._eval(node.slice, env, record)
        parts = self._index_parts(node.slice)
        if all(self._index_broadcast(p) for p in parts):
            return base  # [:, None]-style reshape: same buffer
        t = base - {WHOLE}
        if not all(self._index_static(p, env) for p in parts):
            t = (t | {GATHER}) - {PY}
        return t

    def _eval_call(self, call: ast.Call, env: dict, record: bool) -> frozenset:
        fname = dotted(call.func)
        last = _last(fname) if fname else ""

        # `<base>.at[idx].method(value)` -- the scatter form.  Result keeps
        # the BASE buffer's provenance (incl. WHOLE); the scattered value /
        # index do not taint the buffer.  Rules inspect the site directly.
        sc = at_scatter(call)
        if sc is not None:
            base_e, index_e, method = sc
            base_t = self._eval(base_e, env, record)
            index_t = self._eval(index_e, env, record)
            value_t = EMPTY
            for a in call.args:
                value_t = value_t | self._eval(a, env, record)
            for kw in call.keywords:
                self._eval(kw.value, env, record)
            if record:
                self.scatters.append(
                    ScatterSite(call, base_e, index_e, method, base_t, index_t, value_t)
                )
            return base_t

        arg_tags = [self._eval(a, env, record) for a in call.args]
        kw_tags = [self._eval(kw.value, env, record) for kw in call.keywords]
        u = frozenset().union(EMPTY, *arg_tags, *kw_tags)
        # method receiver (`row.sum()`, `t.take(idx)`): the result derives
        # from the receiver too -- module receivers (jnp.sum) carry EMPTY
        recv = (
            self._eval(call.func.value, env, record)
            if isinstance(call.func, ast.Attribute)
            else EMPTY
        )

        # jax control flow
        if fname in _LOOP_CALLS and record and self.depth < _MAX_DEPTH:
            idx = 1 if fname.endswith("while_loop") else 2
            bodies = self._loop_body_analyses(call, idx, env)
            self.ma._loop_sites.append(LoopSite(call, bodies))
        if fname in _BRANCH_CALLS:
            # Branches resolve in BOTH passes: the fixpoint must use the
            # same transfer function as annotation, or a cond result that
            # crosses a basic-block boundary converges under-tainted
            # (WHOLE stripped) and the branch-provenance rules go blind.
            branches = (
                self._branch_analyses(call, env, arg_tags)
                if self.depth < _MAX_DEPTH
                else []
            )
            if branches:
                if record:
                    self.branch_sites.append(BranchSite(call, branches))
                return frozenset().union(EMPTY, *(b.return_tags for b in branches))
            return _generic_call(u)

        # sharding provenance (checked before the helper summary so the
        # repo's own shard_problem keeps its canonical meaning)
        if last in _SHARD_MAKERS:
            return (u | {SHARD}) - {PY}
        if last == "device_put":
            placed = bool(
                len(call.args) >= 2
                and not (
                    isinstance(call.args[1], ast.Constant)
                    and call.args[1].value is None
                )
            ) or any(kw.arg in ("device", "sharding") for kw in call.keywords)
            base_t = arg_tags[0] if arg_tags else EMPTY
            return (base_t | {SHARD}) - {PY} if placed else base_t

        # container mutators: the element's tags merge into the receiver
        # binding (list-of-closures flow); the call itself returns None
        if (
            isinstance(call.func, ast.Attribute)
            and last in _CONTAINER_MUTATORS
            and not fname.startswith(("jnp.", "np.", "jax.", "lax.", "math."))
        ):
            key = dotted(call.func.value)
            if key:
                env[key] = env.get(key, recv) | (u - {PY})
            return EMPTY

        # provenance-aware builtins
        if last in _REDUCERS:
            t = (u | recv) - {GATHER, WHOLE, PY}
            if last in _ASSOC_REDUCERS:
                t = t | {REDUCED}
            return t
        if last in _CUMULATIVE or last in _ASSOC_REDUCERS:
            # cumsum-style (shape-preserving partial sums) and the
            # contraction ops (dot/matmul/einsum) that _REDUCERS omits
            return _generic_call(u | recv) | {REDUCED}
        if last in _WHERE_LIKE:
            return u | recv  # whole-buffer select keeps whole
        if last in _WHOLE_PRESERVING:
            return recv | (u - {WHOLE, PY})
        if last in _GATHER_ADDERS:
            return (((u | recv) | {GATHER}) - {WHOLE}) - {PY}
        if last in _PY_KEEPERS:
            return frozenset({PY})

        # multi-hop summary for module-local and imported project helpers
        # (summary analyses run at _MAX_DEPTH so jax-site/nested-def
        # resolution stays off inside them; the HOP budget is what lets a
        # summarized callee's own calls summarize in turn)
        if self.hops < _MAX_SUMMARY_HOPS and fname:
            kw_map = {
                kw.arg: t
                for kw, t in zip(call.keywords, kw_tags)
                if kw.arg is not None
            }
            target = self.ma.module_defs.get(fname)
            if target is not None:
                summary = self.ma.call_summary(
                    target, arg_tags, kw_map, hops=self.hops + 1
                )
                if summary is not None:
                    return summary
            else:
                imported = self.ma.imported_def(fname)
                if imported is not None:
                    target_ma, target_fn = imported
                    summary = target_ma.call_summary(
                        target_fn, arg_tags, kw_map, hops=self.hops + 1
                    )
                    if summary is not None:
                        return summary

        # generic call: union of arguments (and the receiver, for methods),
        # minus whole/py -- the result is a new value
        return _generic_call(u | recv)

    def _branch_analyses(self, call: ast.Call, env: dict, arg_tags: list) -> list:
        fname = dotted(call.func)
        if fname.endswith("cond"):
            cands = []
            for arg in call.args[1:3]:
                cands.extend(self.resolve_callables(arg))
            op_tags = arg_tags[3:]
        else:  # switch
            cands = self.resolve_callable_list(call.args[1]) if len(call.args) > 1 else []
            op_tags = arg_tags[2:]
        out = []
        for fn, owner in cands:
            params = _all_args(getattr(fn, "args", None))
            seeds = {
                p.arg: (op_tags[i] if i < len(op_tags) else EMPTY)
                for i, p in enumerate(params)
            }
            fa = self.ma.analyze_resolved(
                fn, owner if owner is not None else self, seeds=seeds, env_hint=env
            )
            if fa is not None:
                out.append(fa)
        return out

    def _loop_body_analyses(self, call: ast.Call, body_idx: int, env: dict) -> list:
        if len(call.args) <= body_idx:
            return []
        out = []
        for fn, owner in self.resolve_callables(call.args[body_idx]):
            args = _all_args(getattr(fn, "args", None)) if getattr(fn, "args", None) else []
            seeds = {}
            for i, a in enumerate(args):
                if body_idx == 2 and i == 0:  # fori_loop index operand
                    seeds[a.arg] = EMPTY
                else:
                    seeds[a.arg] = frozenset({CARRY, WHOLE})
            fa = self.ma.analyze_resolved(fn, owner or self, seeds=seeds, env_hint=env)
            if fa is not None:
                out.append(fa)
        return out


def _all_args(args: Optional[ast.arguments]) -> list[ast.arg]:
    if args is None:
        return []
    out = list(getattr(args, "posonlyargs", [])) + list(args.args) + list(args.kwonlyargs)
    if args.vararg:
        out.append(args.vararg)
    if args.kwarg:
        out.append(args.kwarg)
    return out


def _closure_of(env: dict, outer_closure: dict) -> dict:
    c = dict(outer_closure)
    c.update(env)
    return c


def _arith(tags: frozenset) -> frozenset:
    """Element arithmetic: a NEW buffer (whole dropped); python-static only
    when every operand was python-static."""
    t = tags - {WHOLE}
    if t & _ARRAYISH:
        t = t - {PY}
    return t


def _generic_call(tags: frozenset) -> frozenset:
    return (tags - {WHOLE}) - {PY}


def _join(a: Optional[dict], b: dict) -> Optional[dict]:
    """Union-join b into a copy of a; None when nothing changed."""
    if a is None:
        return dict(b)
    changed = False
    out = dict(a)
    for k, v in b.items():
        old = out.get(k)
        if old is None:
            out[k] = v
            changed = True
        elif not v <= old:
            out[k] = old | v
            changed = True
    return out if changed else None


def _join_into(acc: dict, env: dict) -> None:
    for k, v in env.items():
        acc[k] = acc.get(k, EMPTY) | v


# --------------------------------------------------------------------------
# module analysis
# --------------------------------------------------------------------------

class ModuleAnalysis:
    """One parsed module: module env + on-demand function analyses + the
    resolved jax control-flow sites rules iterate."""

    def __init__(self, tree: ast.Module, relpath: str = "<module>"):
        self.tree = tree
        self.relpath = relpath
        self.module_defs: dict[str, ast.AST] = {}
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_defs[stmt.name] = stmt
        self._fa_cache: dict = {}
        self._summary_cache: dict = {}
        self._in_progress: set = set()
        self._parents: dict[int, FunctionAnalysis] = {}
        self._loop_sites: list[LoopSite] = []
        self.module_env: dict[str, frozenset] = {}
        self.module_fa: Optional[FunctionAnalysis] = None
        # project modules consulted via imported_def (relpaths; dep_hashes
        # closes this transitively for the CLI's --cache key)
        self.deps: set[str] = set()
        self._method_class_by_id: dict[int, str] = {}
        self._class_fields: dict[str, dict[str, frozenset]] = {}
        self._fields_ready = False
        # import maps MUST exist before the module pass: _eval_call chases
        # imported summaries while top-level defs analyze
        self._import_from: dict[str, tuple[str, str]] = {}
        self._import_mod: dict[str, str] = {}
        self._collect_imports(tree)
        # module pass: binds module-level names (constants -> PY, imports ->
        # empty) and eagerly analyzes top-level defs as children
        self.module_fa = FunctionAnalysis(self, tree, seeds={}, closure={})
        self._register(self.module_fa, None)
        self.module_env = self.module_fa.exit_env
        self._build_class_fields()
        if self._class_fields:
            # second pass: `self.X` reads now see the cross-method field
            # map (the first pass recorded their tags before it existed)
            self._loop_sites.clear()
            self.module_fa = FunctionAnalysis(self, tree, seeds={}, closure={})
            self._register(self.module_fa, None)
            self.module_env = self.module_fa.exit_env
            self._build_class_fields()

    # bookkeeping -----------------------------------------------------------

    def _register(self, fa: FunctionAnalysis, parent: Optional[FunctionAnalysis]) -> None:
        if parent is not None:
            self._parents[id(fa)] = parent

    def parent_of(self, fa: FunctionAnalysis) -> Optional[FunctionAnalysis]:
        return self._parents.get(id(fa))

    # classes ---------------------------------------------------------------

    def note_method(self, classname: str, fn) -> None:
        self._method_class_by_id[id(fn)] = classname

    def method_class(self, fn) -> Optional[str]:
        return self._method_class_by_id.get(id(fn))

    def class_field_tags(self, classname: str) -> dict:
        """Flow-insensitive union of `self.X = ...` bindings across every
        method of the class (empty until the first module pass completes)."""
        return self._class_fields.get(classname, {})

    def _build_class_fields(self) -> None:
        fields: dict[str, dict[str, frozenset]] = {}
        for fa in self.module_fa.tree():
            cls = self._method_class_by_id.get(id(fa.fn))
            if cls is None:
                continue
            for k, v in fa.exit_env.items():
                if k.startswith("self.") and "." not in k[5:]:
                    d = fields.setdefault(cls, {})
                    d[k[5:]] = d.get(k[5:], EMPTY) | v
        self._class_fields = fields
        self._fields_ready = True

    # imports ---------------------------------------------------------------

    def _package(self) -> Optional[str]:
        rp = self.relpath
        if not rp.endswith(".py"):
            return None
        parts = rp[:-3].replace(os.sep, "/").split("/")
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts[:-1])

    def _collect_imports(self, tree: ast.Module) -> None:
        pkg = self._package()
        for stmt in tree.body:
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    if alias.asname:
                        self._import_mod[alias.asname] = alias.name
                    else:
                        root = alias.name.split(".")[0]
                        self._import_mod[root] = root
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level:
                    if pkg is None:
                        continue
                    parts = pkg.split(".") if pkg else []
                    cut = stmt.level - 1
                    if cut:
                        if cut > len(parts):
                            continue
                        parts = parts[:-cut] if cut else parts
                    base = ".".join(parts)
                    mod = base + "." + stmt.module if stmt.module else base
                    if not mod:
                        continue
                else:
                    mod = stmt.module or ""
                for alias in stmt.names:
                    if alias.name == "*":
                        continue
                    self._import_from[alias.asname or alias.name] = (mod, alias.name)

    def imported_def(self, fname: str):
        """(ModuleAnalysis, def node) for a callable imported from another
        PROJECT module: `helper(...)` via `from m import helper`, or
        `m.helper(...)` via `import m` / `from pkg import m`.  None when
        the target lives outside the project root (stdlib, jax, numpy) or
        sits on an import cycle (caller falls back to the generic call)."""
        if "." not in fname:
            ent = self._import_from.get(fname)
            if ent is None:
                return None
            modname, orig = ent
            pm = project_module(modname)
            if pm is None:
                return None
            self.deps.add(pm.relpath)
            fn = pm.module_defs.get(orig)
            return (pm, fn) if fn is not None else None
        head, func = fname.rsplit(".", 1)
        modname = None
        if head in self._import_mod:
            modname = self._import_mod[head]
        elif head in self._import_from:
            m, orig = self._import_from[head]
            modname = m + "." + orig if m else orig
        elif "." in head:
            root = head.split(".")[0]
            if self._import_mod.get(root) == root:
                modname = head  # `import a.b` then `a.b.helper(...)`
        if modname is None:
            return None
        pm = project_module(modname)
        if pm is None:
            return None
        self.deps.add(pm.relpath)
        fn = pm.module_defs.get(func)
        return (pm, fn) if fn is not None else None

    # analyses --------------------------------------------------------------

    def function_analysis(self, fn, seeds: Optional[dict] = None) -> FunctionAnalysis:
        """Analyze a module-level def with generic seeds (params = ext+whole
        unless overridden)."""
        key = (id(fn), _seed_key(seeds))
        fa = self._fa_cache.get(key)
        if fa is None:
            if key in self._in_progress:
                return None  # recursion: caller falls back to generic
            self._in_progress.add(key)
            try:
                default = {a.arg: frozenset({EXT, WHOLE}) for a in _all_args(getattr(fn, "args", None))}
                if seeds:
                    default.update({k: frozenset(v) for k, v in seeds.items()})
                fa = FunctionAnalysis(self, fn, seeds=default, closure={})
                self._fa_cache[key] = fa
                self._register(fa, getattr(self, "module_fa", None))
            finally:
                self._in_progress.discard(key)
        return fa

    def analyze_resolved(self, fn, owner: FunctionAnalysis, seeds: dict, env_hint: Optional[dict]) -> Optional[FunctionAnalysis]:
        """Analyze a resolved callable in its defining context: closure =
        the env snapshot at its def site (falling back to the call-site env
        for same-scope defs)."""
        key = (id(fn), _seed_key(seeds), id(owner))
        fa = self._fa_cache.get(key)
        if fa is not None:
            return fa
        if key in self._in_progress or len(self._in_progress) > 64:
            return None
        closure = owner.def_site_env.get(id(fn))
        if closure is None:
            closure = env_hint if env_hint is not None else owner.exit_env
        closure = _closure_of(closure, owner.closure)
        self._in_progress.add(key)
        try:
            fa = FunctionAnalysis(
                self, fn, seeds=seeds, closure=closure, depth=owner.depth + 1,
                hops=owner.hops,
            )
            self._fa_cache[key] = fa
            self._register(fa, owner)
        finally:
            self._in_progress.discard(key)
        return fa

    def call_summary(self, fn, arg_tags: list, kw_map: dict, hops: int = 1) -> Optional[frozenset]:
        """Return-tag summary of a module-local (or project-imported)
        helper, memoized by (callee, argument-tag signature, hop position).
        Summary analyses run at _MAX_DEPTH -- no nested-def or jax-site
        resolution inside them -- but carry the caller's hop position, so a
        summarized callee's OWN helper calls summarize in turn until the
        _MAX_SUMMARY_HOPS budget runs out.  Tag sort is key=repr: marker
        tuples (helper_flow_args) and plain strings share the sets."""
        sig = (
            id(fn),
            hops,
            tuple(tuple(sorted(t, key=repr)) for t in arg_tags),
            tuple(sorted(((k, tuple(sorted(v, key=repr))) for k, v in kw_map.items()), key=repr)),
        )
        if sig in self._summary_cache:
            return self._summary_cache[sig]
        if sig in self._in_progress:
            return None
        self._in_progress.add(sig)
        try:
            params = _all_args(getattr(fn, "args", None))
            seeds: dict = {}
            for i, p in enumerate(params):
                seeds[p.arg] = arg_tags[i] if i < len(arg_tags) else EMPTY
            for name, tags in kw_map.items():
                if any(p.arg == name for p in params):
                    seeds[name] = tags
            fa = FunctionAnalysis(
                self, fn, seeds=seeds, closure={}, depth=_MAX_DEPTH, hops=hops
            )
            result = fa.return_tags
            self._summary_cache[sig] = result
            return result
        finally:
            self._in_progress.discard(sig)

    # site iterators --------------------------------------------------------

    def loop_sites(self) -> list[LoopSite]:
        """Every lax.while_loop/fori_loop call in the module with resolved
        body analyses (body params seeded as the loop carry).  Deduplicated
        by call node -- enclosing functions analyzed under several seed
        signatures register the same site more than once."""
        seen: set[int] = set()
        out = []
        for site in self._loop_sites:
            if id(site.call) in seen:
                continue
            seen.add(id(site.call))
            out.append(site)
        return out

    def jit_sites(self) -> list[JitSite]:
        """Every jax.jit application: decorated defs and direct calls.
        The traced function is analyzed with params = carry+whole (its
        operands ARE the big buffers)."""
        out: list[JitSite] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    shard = _jit_out_shardings(deco)
                    if shard is _NOT_JIT:
                        continue
                    fa = self._traced_fa(node)
                    out.append(JitSite(deco, node, fa, shard))
            elif isinstance(node, ast.Call):
                if dotted(node.func) in ("jax.jit", "jit") and node.args:
                    shard = _kwarg_state(node)
                    for fn, _owner in self.module_fa.resolve_callables(node.args[0]):
                        fa = self._traced_fa(fn)
                        out.append(JitSite(node, fn, fa, shard))
        return out

    def _traced_fa(self, fn) -> Optional[FunctionAnalysis]:
        seeds = {a.arg: frozenset({CARRY, WHOLE}) for a in _all_args(getattr(fn, "args", None))}
        owner = None
        # find the defining analysis so closures resolve
        for fa in self.module_fa.tree():
            if id(fn) in fa.def_site_env or fn in getattr(fa.fn, "body", []):
                owner = fa
                break
        if owner is None:
            owner = self.module_fa
        return self.analyze_resolved(fn, owner, seeds=seeds, env_hint=None)


_NOT_JIT = object()


def _kwarg_state(call: ast.Call):
    """True/False/None out_shardings visibility for a call node."""
    state: object = False
    for kw in call.keywords:
        if kw.arg == "out_shardings":
            if isinstance(kw.value, ast.Constant) and kw.value.value is None:
                return False
            return True
        if kw.arg is None:  # **splat hides the signature
            state = None
    return state


def _jit_out_shardings(deco: ast.AST):
    """Classify a decorator: _NOT_JIT, or the out_shardings state of a jit
    application (`@jax.jit`, `@jax.jit(...)`,
    `@functools.partial(jax.jit, ...)`)."""
    if isinstance(deco, (ast.Name, ast.Attribute)):
        return False if dotted(deco) in ("jax.jit", "jit") else _NOT_JIT
    if not isinstance(deco, ast.Call):
        return _NOT_JIT
    fname = dotted(deco.func)
    if fname in ("jax.jit", "jit"):
        return _kwarg_state(deco)
    if _last(fname) == "partial" and deco.args and dotted(deco.args[0]) in ("jax.jit", "jit"):
        return _kwarg_state(deco)
    return _NOT_JIT


def _seed_key(seeds: Optional[dict]):
    if not seeds:
        return ()
    return tuple(sorted(((k, tuple(sorted(v, key=repr))) for k, v in seeds.items()), key=repr))


# --------------------------------------------------------------------------
# project registry (cross-module summaries + --cache invalidation keys)
# --------------------------------------------------------------------------

_PROJECT_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_PM_CACHE: dict = {}  # modname -> (content_hash, ModuleAnalysis | None)
_PM_BUILDING: set = set()
_HASHES: dict = {}  # relpath -> content hash of the analyzed bytes


def set_project_root(root: str) -> None:
    """Point the cross-module resolver at a different tree (tests)."""
    global _PROJECT_ROOT
    _PROJECT_ROOT = os.path.abspath(root)
    _PM_CACHE.clear()
    _PM_BUILDING.clear()
    _HASHES.clear()


def content_hash(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def project_module(modname: str) -> Optional[ModuleAnalysis]:
    """ModuleAnalysis for a dotted module name under the project root,
    keyed by content hash (a re-read after the file changed re-analyzes).
    None for modules outside the root, unparsable files, and import
    cycles (the in-progress guard -- callers fall back to generic)."""
    if not modname or modname.startswith("."):
        return None
    base = os.path.join(_PROJECT_ROOT, *modname.split("."))
    path = base + ".py"
    if not os.path.isfile(path):
        path = os.path.join(base, "__init__.py")
        if not os.path.isfile(path):
            return None
    try:
        h = content_hash(path)
    except OSError:
        return None
    cached = _PM_CACHE.get(modname)
    if cached is not None and cached[0] == h:
        return cached[1]
    if modname in _PM_BUILDING:
        return None
    _PM_BUILDING.add(modname)
    try:
        try:
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            _PM_CACHE[modname] = (h, None)
            return None
        rel = os.path.relpath(path, _PROJECT_ROOT)
        ma = ModuleAnalysis(tree, rel)
        _PM_CACHE[modname] = (h, ma)
        _HASHES[rel] = h
        return ma
    finally:
        _PM_BUILDING.discard(modname)


def dep_hashes(ma: ModuleAnalysis) -> dict:
    """relpath -> content hash for every project module this analysis
    consulted, TRANSITIVELY (the CLI --cache entry is stale when any of
    these changes, not just the linted file itself)."""
    by_rel = {
        m.relpath: m
        for _h, m in _PM_CACHE.values()
        if m is not None
    }
    out: dict = {}
    work = list(ma.deps)
    seen: set = set()
    while work:
        rel = work.pop()
        if rel in seen:
            continue
        seen.add(rel)
        h = _HASHES.get(rel)
        if h is not None:
            out[rel] = h
        dep_ma = by_rel.get(rel)
        if dep_ma is not None:
            work.extend(dep_ma.deps)
    return out


def helper_flow_args(ma: ModuleAnalysis, call: ast.Call) -> Optional[list]:
    """Which of `call`'s argument EXPRESSIONS flow into the callee's return
    value.  The callee (module-local or project-imported) is summarized
    with unique per-parameter marker tags; markers surviving into the
    return map back to the call's argument expressions.  None when the
    callee is unresolvable -- rules fall back to their local handling.

    This is the re-homing facility for the value-flow ingest rules: a
    binding `x = normalize(positions)` lets a rule union its own domain
    tags over `positions` instead of losing provenance at the helper."""
    fname = dotted(call.func)
    if not fname:
        return None
    target = ma.module_defs.get(fname)
    target_ma = ma
    if target is None:
        imp = ma.imported_def(fname)
        if imp is None:
            return None
        target_ma, target = imp
    params = _all_args(getattr(target, "args", None))
    if not params:
        return None
    exprs: dict = {}
    for i, a in enumerate(call.args):
        if i < len(params):
            exprs[params[i].arg] = a
    for kw in call.keywords:
        if kw.arg is not None:
            exprs[kw.arg] = kw.value
    arg_tags = [frozenset({("param", p.arg)}) for p in params]
    summary = target_ma.call_summary(target, arg_tags, {}, hops=1)
    if summary is None:
        return None
    out = []
    for tag in summary:
        if isinstance(tag, tuple) and len(tag) == 2 and tag[0] == "param":
            e = exprs.get(tag[1])
            if e is not None:
                out.append(e)
    return out


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------

def analyze(tree: ast.Module, relpath: str = "<module>") -> ModuleAnalysis:
    return ModuleAnalysis(tree, relpath)


def of(src) -> ModuleAnalysis:
    """Memoized per-Source analysis (lint rules share one pass per file)."""
    ma = getattr(src, "_dataflow", None)
    if ma is None:
        ma = analyze(src.tree, getattr(src, "relpath", "<module>"))
        src._dataflow = ma
    return ma
