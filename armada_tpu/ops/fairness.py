"""Dominant-resource fairness on device.

Reference semantics:
- cost of an allocation = max(0, max_r(alloc_r / total_r * multiplier_r)), weighted
  cost divides by queue weight (fairness.go:99-103, DivideZeroOnError -> 0 where
  total_r == 0).
- Fair shares are computed by iterative water-filling that re-shares capacity queues
  don't demand (context/scheduling.go updateFairShares:220-300): at most 10
  iterations, stopping once <=1% of capacity remains unallocated.

The Go version walks sorted queue structs; here every step is a [Q]-vector op, so one
iteration is a handful of VPU instructions regardless of queue count.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


def unweighted_drf_cost(alloc, total, multipliers):
    """DRF cost of allocation(s) `alloc[..., R]` against pool totals `total[R]`.

    Matches fairness.go UnweightedCostFromAllocation:103: per-resource fraction of
    pool total, scaled by the configured multiplier, dominant (max) reduced; zero
    totals contribute zero (DivideZeroOnError).
    """
    safe_total = jnp.where(total > 0, total, 1.0)
    frac = jnp.where(total > 0, alloc / safe_total, 0.0) * multipliers
    return jnp.maximum(0.0, jnp.max(frac, axis=-1))


def weighted_drf_cost(alloc, total, multipliers, weight):
    """fairness.go WeightedCostFromAllocation:99: unweighted cost / queue weight."""
    safe_w = jnp.where(weight > 0, weight, 1.0)
    return jnp.where(weight > 0, unweighted_drf_cost(alloc, total, multipliers) / safe_w, 0.0)


class FairShares(NamedTuple):
    """Per-queue share vectors (context/queue.go QueueSchedulingContext fields)."""

    fair_share: jax.Array  # weight / sum-of-weights
    demand_capped_adjusted_fair_share: jax.Array  # share given current demand
    uncapped_adjusted_fair_share: jax.Array  # share if demand were infinite


def theoretical_share(weights, constrained_demand_share, priority: float) -> float:
    """The demand-capped adjusted fair share a NEW queue with weight
    1/priority and unbounded demand would receive alongside the given queues
    (context/scheduling.go CalculateTheoreticalShare:199)."""
    import numpy as np

    w = np.append(np.asarray(weights, np.float32), np.float32(1.0 / priority))
    cds = np.append(
        np.asarray(constrained_demand_share, np.float32), np.float32(1.0)
    )
    shares = fair_shares(w, cds)
    return float(np.asarray(shares.demand_capped_adjusted_fair_share)[-1])


def fair_shares(weights, constrained_demand_share, *, max_iterations: int = 10) -> FairShares:
    """Water-filling fair-share computation over [Q] vectors.

    `weights[q]` must be 0 for padding/absent queues (they then receive zero shares
    and never absorb capacity).  `constrained_demand_share[q]` is the DRF cost of the
    queue's constraint-capped demand (scheduling_algo.go:486-573 computes this from
    demand capped by per-queue limits).

    Mirrors context/scheduling.go updateFairShares:220-300 exactly, including the
    iteration-order subtleties: the uncapped share update uses the *previous*
    iteration's spare shares, and the loop breaks after the uncapped update when all
    queues have achieved demand.

    JITTED (round 17): the eager path closed over weights/cds inside the
    while_loop body, embedding fresh constant arrays in the jaxpr every
    call -- jax's primitive cache missed and XLA recompiled the loop on
    EVERY invocation (~49ms/call measured on the CPU host; with
    queue_stats_from_result calling this once per pool per cycle, an
    8-pool cycle burned ~0.4s pure recompilation).  As traced arguments
    they key the compile cache on shape only; inside an enclosing jit
    (the round kernel) the inner jit inlines as before.
    """
    return _fair_shares_jit(
        jnp.asarray(weights, jnp.float32),
        jnp.asarray(constrained_demand_share, jnp.float32),
        max_iterations=max_iterations,
    )


import functools as _functools


@_functools.partial(jax.jit, static_argnames=("max_iterations",))
def _fair_shares_jit(weights, cds, *, max_iterations: int) -> FairShares:
    weight_sum = jnp.sum(weights)
    fair_share = jnp.where(weight_sum > 0, weights / jnp.where(weight_sum > 0, weight_sum, 1.0), 0.0)

    def cond(state):
        i, unallocated, running, _, _, _, _ = state
        return (i < max_iterations) & (unallocated > 0.01) & running

    def body(state):
        i, unallocated, running, achieved, spare, dcafs, ucafs = state
        active_w = jnp.where(achieved, 0.0, weights)
        total_weight = jnp.sum(active_w)
        # Uncapped share: every queue takes its weight-share of (unallocated minus its
        # own spare), as if it alone had infinite demand (scheduling.go:260-272).
        denom = total_weight + jnp.where(achieved, weights, 0.0)
        take = jnp.where(denom > 0, weights / jnp.where(denom > 0, denom, 1.0), 0.0)
        ucafs = ucafs + take * (unallocated - spare)
        # scheduling.go:274-276 -- all demand achieved: stop (after ucafs update).
        running = total_weight > 0.0
        # Demand-capped share for queues still short of demand (scheduling.go:278-284).
        safe_tw = jnp.where(total_weight > 0, total_weight, 1.0)
        add = jnp.where(achieved | (total_weight <= 0), 0.0, weights / safe_tw * unallocated)
        dcafs = dcafs + add
        # Clip to demand; overspill becomes next iteration's unallocated pool
        # (scheduling.go:286-297).
        spare_new = dcafs - cds
        newly_achieved = spare_new > 0.0
        dcafs = jnp.where(newly_achieved, cds, dcafs)
        spare = jnp.where(newly_achieved, spare_new, 0.0)
        achieved = achieved | newly_achieved
        unallocated = jnp.where(running, jnp.sum(spare * newly_achieved), 0.0)
        # Keep non-running exit consistent with the Go break: when running is False we
        # leave dcafs untouched above (add==0) and the loop condition ends it.
        return (i + 1, unallocated, running, achieved, spare, dcafs, ucafs)

    q = weights.shape[0]
    zeros = jnp.zeros((q,), jnp.float32)
    init = (
        jnp.int32(0),
        jnp.float32(1.0),
        jnp.bool_(True),
        jnp.zeros((q,), bool),
        zeros,
        zeros,
        zeros,
    )
    _, _, _, _, _, dcafs, ucafs = jax.lax.while_loop(cond, body, init)
    return FairShares(fair_share, dcafs, ucafs)
