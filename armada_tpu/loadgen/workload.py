"""Seeded workload mix: what each due arrival event *is*.

Turns a count of due arrivals (from loadgen/arrivals) into concrete
operations against the submit surface: single-job submits, gang submits,
cancels and reprioritisations of previously-submitted jobs -- all drawn
from one seeded RNG, so the traffic a soak run applies is a deterministic
function of (MixConfig, seed) even though the *times* come from a separate
arrival process.  Cancel/reprioritise targets are sampled from the
generator's own live-id pool, which the driver feeds back from the submit
responses (ids are server-assigned).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional, Sequence

from armada_tpu.server.submit import JobSubmitItem


@dataclasses.dataclass(frozen=True)
class MixConfig:
    """The event mix.  Weights need not sum to 1 (normalised); a cancel or
    reprioritise with no live target degrades to a submit, so the achieved
    mix converges to the configured one once the pool warms up."""

    submit_weight: float = 0.85
    cancel_weight: float = 0.05
    reprioritize_weight: float = 0.10
    # Fraction of submit events that open a gang (the whole gang rides ONE
    # arrival event: gangs are atomic from the submitter's perspective).
    gang_fraction: float = 0.05
    gang_size_min: int = 2
    gang_size_max: int = 4
    num_queues: int = 4
    queue_prefix: str = "soak"
    jobset: str = "soak"
    # Job shapes drawn uniformly (cpu, memory) -- small relative to the
    # node shape so the fake cluster turns jobs over.
    cpu_choices: Sequence[str] = ("1", "2", "4")
    memory_choices: Sequence[str] = ("1", "2")
    priority_levels: int = 4
    # Heterogeneous-fleet mix: this fraction of submits carries a node-type
    # throughput map drawn over `node_types` (riding the submit annotation,
    # so the soak exercises the full parse -> key -> kernel-bias path).
    # Empty node_types or 0.0 = every job type-insensitive (the default
    # mix, bit-identical to pre-heterogeneity runs).
    type_sensitive_fraction: float = 0.0
    node_types: Sequence[str] = ()
    throughput_choices: Sequence[float] = (0.5, 1.0, 2.0, 4.0)


@dataclasses.dataclass
class SubmitOp:
    queue: str
    items: list  # JobSubmitItem
    gang: bool = False


@dataclasses.dataclass
class CancelOp:
    queue: str
    job_ids: list


@dataclasses.dataclass
class ReprioritizeOp:
    queue: str
    job_ids: list
    priority: int


class WorkloadGenerator:
    """Deterministic op stream.  One `next_ops(n)` call consumes n arrival
    events; the driver applies the returned ops in order and feeds the
    submit responses back via `note_submitted`."""

    def __init__(self, mix: MixConfig, seed: int = 0):
        self.mix = mix
        self._rng = random.Random(seed)
        self.queues = [
            f"{mix.queue_prefix}-{i}" for i in range(mix.num_queues)
        ]
        # Per-queue live candidate ids for cancel/reprioritise targeting.
        # "Live" is from the generator's view (submitted, not yet cancelled
        # by us): a target that already finished server-side is fine -- a
        # cancel of a terminal job is a legal no-op the plane must absorb.
        self._live: dict[str, list] = {q: [] for q in self.queues}
        self._gang_seq = 0
        self.counts = {"submit": 0, "gang_jobs": 0, "cancel": 0, "reprioritize": 0}

    # ------------------------------------------------------------ feeding ---

    def note_submitted(self, queue: str, job_ids: Sequence[str]) -> None:
        self._live[queue].extend(job_ids)

    def live_count(self) -> int:
        return sum(len(v) for v in self._live.values())

    # ---------------------------------------------------------- generating --

    def _item(self) -> JobSubmitItem:
        rng = self._rng
        annotations = {}
        mix = self.mix
        if mix.node_types and rng.random() < mix.type_sensitive_fraction:
            # Whitelist of 1..all fleet types with per-type throughputs; at
            # least one type stays admitted so the job is schedulable (the
            # SubmitChecker unknown-type rejection has its own unit drill).
            k = 1 + rng.randrange(len(mix.node_types))
            chosen = rng.sample(list(mix.node_types), k)
            from armada_tpu.core.types import NODE_TYPE_SCORES_ANNOTATION

            annotations[NODE_TYPE_SCORES_ANNOTATION] = ",".join(
                f"{t}={rng.choice(mix.throughput_choices)}" for t in chosen
            )
            self.counts["type_sensitive"] = (
                self.counts.get("type_sensitive", 0) + 1
            )
        return JobSubmitItem(
            resources={
                "cpu": rng.choice(self.mix.cpu_choices),
                "memory": rng.choice(self.mix.memory_choices),
            },
            priority=rng.randrange(self.mix.priority_levels),
            annotations=annotations,
        )

    def _pick_targets(self, rng: random.Random, k_max: int = 8):
        """(queue, ids) from the live pool, or None when the pool is cold."""
        candidates = [q for q in self.queues if self._live[q]]
        if not candidates:
            return None
        q = rng.choice(candidates)
        pool = self._live[q]
        k = min(len(pool), 1 + rng.randrange(k_max))
        # Sample WITHOUT replacement and remove: each id is targeted at most
        # once, so the lifecycle tracker can treat our cancels as definitive.
        idxs = sorted(rng.sample(range(len(pool)), k), reverse=True)
        ids = [pool[i] for i in idxs]
        for i in idxs:
            pool.pop(i)
        return q, ids

    def next_ops(self, n_events: int) -> list:
        """Consume n arrival events; returns a list of ops.  Multiple
        consecutive submit events to the same queue coalesce into one
        SubmitOp (one wire batch), which is how a real client at high
        event rates batches too."""
        mix = self.mix
        rng = self._rng
        total_w = mix.submit_weight + mix.cancel_weight + mix.reprioritize_weight
        ops: list = []
        pending: dict[str, SubmitOp] = {}

        def flush_pending():
            for op in pending.values():
                ops.append(op)
            pending.clear()

        for _ in range(n_events):
            r = rng.random() * total_w
            if r >= mix.submit_weight:
                kind = "cancel" if r < mix.submit_weight + mix.cancel_weight else "reprioritize"
                hit = self._pick_targets(rng)
                if hit is not None:
                    flush_pending()  # preserve op order around mutations
                    q, ids = hit
                    if kind == "cancel":
                        ops.append(CancelOp(q, ids))
                        self.counts["cancel"] += 1
                    else:
                        ops.append(
                            ReprioritizeOp(
                                q, ids, rng.randrange(mix.priority_levels)
                            )
                        )
                        self.counts["reprioritize"] += 1
                    continue
                # cold pool: degrade to a submit (the arrival still happened)
            q = rng.choice(self.queues)
            if rng.random() < mix.gang_fraction:
                size = rng.randint(mix.gang_size_min, mix.gang_size_max)
                self._gang_seq += 1
                gid = f"gang-{self._gang_seq}"
                items = []
                for _m in range(size):
                    it = self._item()
                    items.append(
                        dataclasses.replace(
                            it, gang_id=gid, gang_cardinality=size
                        )
                    )
                flush_pending()
                ops.append(SubmitOp(q, items, gang=True))
                self.counts["gang_jobs"] += size
            else:
                op = pending.get(q)
                if op is None:
                    op = pending[q] = SubmitOp(q, [])
                op.items.append(self._item())
            self.counts["submit"] += 1
        flush_pending()
        return ops
