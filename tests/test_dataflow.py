"""The dataflow engine itself (analysis/dataflow.py), independent of any
lint rule: def-use + provenance-lattice behavior pinned on the exact
binding shapes the rules walk through (walrus, augmented assign, tuple
unpack, comprehensions, closure capture) plus the jax-site resolution
(loop bodies, cond/switch branches, jit applications).  A rule bug and an
engine bug must fail DIFFERENT tests -- rules are pinned in
tests/test_lint.py against fixtures, the lattice is pinned here against
`exit_env`/`tags()` directly.
"""

from __future__ import annotations

import ast
import textwrap

from armada_tpu.analysis import dataflow as df

G, C, E, W, P, S = df.GATHER, df.CARRY, df.EXT, df.WHOLE, df.PY, df.SHARD


def analyze(src: str) -> df.ModuleAnalysis:
    return df.analyze(ast.parse(textwrap.dedent(src)))


def fn_exit(src: str, name: str = "f", seeds=None) -> dict:
    """exit_env of a module-level def analyzed with default seeds
    (params = {ext, whole}) unless overridden."""
    ma = analyze(src)
    fa = ma.function_analysis(ma.module_defs[name], seeds=seeds)
    return fa.exit_env


# ---------------------------------------------------------------- binding --


def test_param_seed_and_simple_assign():
    env = fn_exit("def f(t):\n    x = t\n    return x\n")
    assert env["x"] == frozenset({E, W})


def test_constant_is_python_static():
    env = fn_exit("def f(t):\n    k = 3\n    s = t.shape\n")
    assert env["k"] == frozenset({P})
    assert env["s"] == frozenset({P})  # shape/ndim/size/dtype reads


def test_walrus_binds_and_yields():
    env = fn_exit("def f(t, i):\n    y = (x := t[i]) + 1\n")
    # the walrus target gets the gathered value; the enclosing arithmetic
    # result keeps the gather taint but is a fresh (non-whole) value
    assert G in env["x"] and W not in env["x"]
    assert G in env["y"] and W not in env["y"]


def test_augmented_assign_unions_and_drops_whole():
    env = fn_exit("def f(t, i):\n    acc = 0\n    acc += t[i]\n")
    assert G in env["acc"]
    assert W not in env["acc"]  # += is element arithmetic, a new buffer
    assert P not in env["acc"]  # arrayish operand absorbs the static int


def test_tuple_unpack_spreads_tags():
    env = fn_exit("def f(c):\n    i, acc = c\n    a, *rest = c\n")
    for name in ("i", "acc", "a", "rest"):
        assert env[name] == frozenset({E, W}), name


def test_comprehension_iterates_rows_not_buffer():
    env = fn_exit("def f(t):\n    out = [r + 1 for r in t]\n")
    # iterating a buffer yields rows (whole dropped), then arithmetic
    assert W not in env["out"] and E in env["out"]


def test_comprehension_over_range_is_static():
    env = fn_exit("def f(t):\n    ks = [k * 2 for k in range(4)]\n")
    assert env["ks"] == frozenset({P})


def test_closure_capture_reads_outer_binding():
    ma = analyze(
        """
        def f(t):
            pre = t * 2
            def g(i):
                return pre
            return g
        """
    )
    fa = ma.function_analysis(ma.module_defs["f"])
    (g_fa,) = [c for c in fa.tree() if c is not fa]
    # `pre` inside g resolves through the def-site closure snapshot:
    # element arithmetic on the param -- ext taint, whole dropped
    assert g_fa.return_tags == frozenset({E})


def test_module_bindings_and_unbound_names():
    ma = analyze("K = 3\ndef f(i):\n    return (K, UNKNOWN)\n")
    fa = ma.function_analysis(ma.module_defs["f"])
    # a module constant is python-static through the module env; a name
    # bound NOWHERE (an undeclared global) defaults to ext provenance
    assert fa.return_tags == frozenset({P, E})


# ---------------------------------------------------- lattice transforms --


def test_subscript_gather_vs_static_vs_broadcast():
    env = fn_exit(
        """
        def f(t, i):
            row = t[i]       # dynamic index: gather, not whole
            head = t[0]      # static index: a row, no gather
            col = t[:, None] # pure broadcast: still the same buffer
        """
    )
    assert env["row"] == frozenset({E, G})
    assert env["head"] == frozenset({E})
    assert env["col"] == frozenset({E, W})


def test_reduction_kills_gather_and_whole():
    env = fn_exit(
        """
        def f(t, i):
            row = t[i]
            s = row.sum()
            m = t.argmin()
        """
    )
    assert env["s"] == frozenset({E})
    assert env["m"] == frozenset({E})


def test_where_preserves_whole_but_generic_call_does_not():
    env = fn_exit(
        """
        import jax.numpy as jnp
        def f(t, m):
            kept = jnp.where(m, t, 0)
            lost = jnp.roll(t, 1)
        """
    )
    assert W in env["kept"]
    assert W not in env["lost"]


def test_take_adds_gather():
    env = fn_exit(
        "import jax.numpy as jnp\ndef f(t, idx):\n    r = jnp.take(t, idx)\n"
    )
    assert G in env["r"] and W not in env["r"]


def test_branch_join_unions_tags():
    env = fn_exit(
        """
        def f(t, i, flag):
            if flag:
                x = t[i]
            else:
                x = 1
        """
    )
    assert env["x"] == frozenset({E, G, P})


def test_loop_fixpoint_accumulates_through_back_edge():
    env = fn_exit(
        """
        def f(t, i):
            acc = 0
            k = i
            while k < 4:
                acc = acc + t[k]
                k = k + 1
        """
    )
    # acc starts python-static; the gathered add only reaches the exit env
    # through the loop back edge, so this pins fixpoint convergence
    assert G in env["acc"] and P in env["acc"]


def test_static_index_loop_is_not_a_gather():
    env = fn_exit(
        """
        def f(t):
            acc = 0
            k = 0
            while k < 4:
                acc = acc + t[k]
                k = k + 1
        """
    )
    # a python-static counter index is trace-time indexing (an unrolled
    # range walk), not a dynamic gather
    assert G not in env["acc"]


def test_one_hop_call_summary_propagates_argument_tags():
    ma = analyze(
        """
        def pick(t, i):
            return t[i]
        def f(t, i):
            r = pick(t, i)
            return r
        """
    )
    fa = ma.function_analysis(ma.module_defs["f"])
    assert G in fa.name_tags("r")


def test_shard_sticky_through_arithmetic_and_scatter():
    env = fn_exit(
        """
        import jax
        def f(t, sharding, rows, idx):
            placed = jax.device_put(t, sharding)
            derived = placed * 2
            scattered = placed.at[idx].set(rows)
        """
    )
    assert S in env["placed"] and S in env["derived"] and S in env["scattered"]


def test_device_put_without_placement_is_not_shard():
    env = fn_exit("import jax\ndef f(t):\n    x = jax.device_put(t)\n")
    assert S not in env["x"]


# ------------------------------------------------------------- jax sites --


def test_while_loop_body_resolved_with_carry_seeds():
    ma = analyze(
        """
        import jax
        def f(table, carry0):
            def body(c):
                i, acc = c
                return (i + 1, acc + table[i])
            return jax.lax.while_loop(lambda c: c[0] < 4, body, carry0)
        """
    )
    sites = ma.loop_sites()
    assert len(sites) == 1
    (body_fa,) = sites[0].bodies
    # the carry param carries CARRY; the closure table read carries EXT
    assert C in body_fa.name_tags("acc")
    assert G in body_fa.return_tags and C in body_fa.return_tags


def test_factory_idiom_resolves_inner_def():
    ma = analyze(
        """
        import jax
        def make_body(table):
            def body(c):
                return c + table[c]
            return body
        def f(table, carry0):
            body = make_body(table)
            return jax.lax.while_loop(lambda c: c < 4, body, carry0)
        """
    )
    sites = ma.loop_sites()
    assert len(sites) == 1 and len(sites[0].bodies) == 1


def test_cond_branch_sites_record_return_tags():
    ma = analyze(
        """
        import jax
        def f(t, hit, row):
            def on_hit(x):
                return x
            def on_miss(x):
                return x[0]
            return jax.lax.cond(hit, on_hit, on_miss, t)
        """
    )
    fa = ma.function_analysis(ma.module_defs["f"])
    (site,) = list(fa.all_branch_sites())
    by_name = {getattr(b.fn, "name", "?"): b.return_tags for b in site.branches}
    assert W in by_name["on_hit"]  # returns the operand buffer itself
    assert W not in by_name["on_miss"]  # returns a row of it


def test_cond_result_keeps_whole_across_block_split():
    """The fixpoint and annotation passes must share ONE transfer function
    for cond/switch results: a statement-level branch between the cond
    binding and its use splits basic blocks, so the use reads the
    CONVERGED env -- if the fixpoint stripped WHOLE (the old generic-call
    approximation), the exact anti-pattern branch-provenance rules exist
    for went invisible."""
    ma = analyze(
        """
        import jax
        def f(table, carry0, p, flag):
            def upd(a):
                return a
            def body(c):
                i, acc = c
                row = jax.lax.cond(p, lambda a: a, upd, table)
                if flag:
                    pass
                y = table[i] * row
                return (i + 1, acc + y[0])
            return jax.lax.while_loop(lambda c: c[0] < 4, body, carry0)
        """
    )
    (site,) = ma.loop_sites()
    (body_fa,) = site.bodies
    assert W in body_fa.name_tags("row")
    assert G in body_fa.name_tags("y") and W not in body_fa.name_tags("y")


def test_scatter_sites_record_base_index_value_tags():
    ma = analyze(
        """
        import jax
        def f(table, i, rows):
            def body(c):
                cand = table[c]
                return table.at[cand].set(rows)
            return jax.lax.while_loop(lambda c: c < 4, body, 0)
        """
    )
    (site,) = ma.loop_sites()
    (body_fa,) = site.bodies
    (sc,) = list(body_fa.all_scatters())
    assert sc.method == "set"
    assert G in sc.index_tags  # indexed by the gathered candidate
    assert W in sc.base_tags and E in sc.base_tags


def test_jit_sites_decorator_call_and_partial_forms():
    ma = analyze(
        """
        import functools
        import jax

        @jax.jit
        def a(x):
            return x

        @functools.partial(jax.jit, donate_argnums=(0,))
        def b(x):
            return x

        @functools.partial(jax.jit, out_shardings=LAYOUT)
        def c(x):
            return x

        d = jax.jit(a, out_shardings=None)

        def e(x, **kw):
            return jax.jit(a, **kw)
        """
    )
    by_fn = {}
    for site in ma.jit_sites():
        by_fn.setdefault(getattr(site.fn, "name", "?"), site.out_shardings)
    assert by_fn["a"] is False  # bare decorator, then jit(a, out_shardings=None)
    assert by_fn["b"] is False  # partial without the kwarg
    assert by_fn["c"] is True  # pinned
    # the **kw splat form: statically undecidable, reported as None
    assert None in {s.out_shardings for s in ma.jit_sites()}


def test_lint_source_memoizes_one_analysis_per_source():
    from armada_tpu.analysis import lint

    src = lint.Source("import jax\nx = 1\n", "armada_tpu/models/m.py")
    assert df.of(src) is df.of(src)
