"""Reference-golden parity traces (VERDICT r3 missing #3 / weak #4).

testdata/golden/*.yaml are hand-derived from the reference simulator's own
test table (ref:internal/scheduler/simulator/simulator_test.go:24-560;
fixtures test_utils.go:92-241) -- the exact ordered event traces the
reference asserts for each cluster/workload world.  Running our simulator
on the same worlds under the mirrored TestSchedulingConfig
(ref:internal/scheduler/testfixtures/testfixtures.go:196-219) and matching
those traces pins our scheduling semantics to the reference's OWN published
expectations, independent of this repo's sequential parity oracles."""

from pathlib import Path

import pytest
import yaml

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.simulator import (
    Simulator,
    cluster_spec_from_dict,
    workload_spec_from_dict,
)

GOLDEN = sorted((Path(__file__).parent.parent / "testdata" / "golden").glob("*.yaml"))

# Trace-kind mapping: the reference publishes a fresh SubmitJob for a
# preempted job's requeue (simulator.go), which our trace records as
# "resubmitted".
KIND = {
    "submitted": "SubmitJob",
    "resubmitted": "SubmitJob",
    "leased": "JobRunLeased",
    "preempted": "JobRunPreempted",
    "succeeded": "JobSucceeded",
    "failed": "JobErrors",
}


def golden_config() -> SchedulingConfig:
    """testfixtures.TestSchedulingConfig mirrored onto our config surface:
    the priority-0..3 ladder (0-2 preemptible), default priority-3,
    prefer-large ordering on, unbounded scheduling bursts."""
    return SchedulingConfig(
        supported_resource_types=(
            ("memory", "1Mi"), ("cpu", "1m"), ("nvidia.com/gpu", "1"),
        ),
        priority_classes={
            "priority-0": PriorityClass("priority-0", priority=0, preemptible=True),
            "priority-1": PriorityClass("priority-1", priority=1, preemptible=True),
            "priority-2": PriorityClass("priority-2", priority=2, preemptible=True),
            "priority-2-non-preemptible": PriorityClass(
                "priority-2-non-preemptible", priority=2, preemptible=False
            ),
            "priority-3": PriorityClass("priority-3", priority=3, preemptible=False),
        },
        default_priority_class="priority-3",
        dominant_resource_fairness_resources=("cpu", "memory", "nvidia.com/gpu"),
        enable_prefer_large_job_ordering=True,
        shape_bucket=8,
        maximum_scheduling_burst=10_000,
        maximum_per_queue_scheduling_burst=10_000,
        maximum_resource_fraction_to_schedule={},
    )


@pytest.mark.parametrize("path", GOLDEN, ids=[p.stem for p in GOLDEN])
def test_golden_trace(path):
    doc = yaml.safe_load(path.read_text())
    sim = Simulator(
        cluster_spec_from_dict(doc["cluster"]),
        workload_spec_from_dict(doc["workload"]),
        golden_config(),
        schedule_interval_s=10.0,  # the reference test's cycle period
    )
    result = sim.run()
    actual = [
        [KIND[kind], _queue_of(sim, jid), _jobset_of(sim, jid)]
        for (_, kind, jid) in result.events
    ]
    expected = [list(e) for e in doc["expected"]]
    assert actual == expected, (
        f"{path.stem}: trace diverged from the reference's golden\n"
        f"expected ({len(expected)}):\n" +
        "\n".join(map(str, expected)) +
        f"\nactual ({len(actual)}):\n" + "\n".join(map(str, actual))
    )
    assert not result.never_scheduled


def _queue_of(sim, jid):
    tmpl = sim.templates[sim.job_template[jid]].template
    return tmpl.queue


def _jobset_of(sim, jid):
    tmpl = sim.templates[sim.job_template[jid]].template
    return tmpl.job_set


def test_golden_traces_with_commit_k_armed(monkeypatch):
    """One full golden pass with ARMADA_COMMIT_K=8 armed (round 15).  The
    golden config runs prefer-large ordering, which schedule_round forces
    back to the single-commit body -- so this pins two things: arming the
    knob can never corrupt a prefer-large round (the force works), and the
    reference's own published traces survive a plane-wide K=8 arm."""
    monkeypatch.setenv("ARMADA_COMMIT_K", "8")
    for path in GOLDEN:
        test_golden_trace(path)
