"""armadactl CLI + `serve` launcher tests: real processes-shaped topology
(control-plane thread + executor thread + CLI against the gRPC port), plus
event-sourced restart recovery of the serve stack.
"""

import threading
import time

import pytest

from armada_tpu.cli.armadactl import main
from armada_tpu.cli.serve import run_fake_executor, start_control_plane
from armada_tpu.core.config import SchedulingConfig


@pytest.fixture
def plane(tmp_path):
    p = start_control_plane(
        str(tmp_path / "data"),
        port=0,
        config=SchedulingConfig(shape_bucket=32),
        cycle_interval_s=0.05,
        schedule_interval_s=0.1,
    )
    yield p
    p.stop()


def ctl(plane, *argv):
    return main(["--url", f"127.0.0.1:{plane.port}", *argv])


def test_cli_full_workflow(plane, tmp_path, capsys):
    assert ctl(plane, "queue", "create", "dev", "--weight", "2") == 0
    assert ctl(plane, "queue", "list") == 0
    out = capsys.readouterr().out
    assert "dev" in out

    # fake executor in a background thread
    stop = threading.Event()
    agent = threading.Thread(
        target=run_fake_executor,
        args=(f"127.0.0.1:{plane.port}",),
        kwargs={
            "executor_id": "t-ex",
            "num_nodes": 2,
            "cpu": "8",
            "memory": "32",
            "interval_s": 0.05,
            "stop": stop,
            "config": SchedulingConfig(shape_bucket=32),
            "default_runtime_s": 0.2,
        },
        daemon=True,
    )
    agent.start()

    sub = tmp_path / "job.yaml"
    sub.write_text(
        """
queue: dev
jobSetId: cli-test
jobs:
  - count: 3
    priority: 0
    resources: {cpu: "2", memory: "1"}
"""
    )
    assert ctl(plane, "submit", str(sub)) == 0
    out = capsys.readouterr().out
    assert "submitted 3 job(s)" in out

    # watch until the jobset drains (idle timeout ends the stream); the
    # generous deadline absorbs a loaded CI host -- the loop exits as soon
    # as all three succeed
    deadline = time.time() + 120
    succeeded = 0
    while time.time() < deadline and succeeded < 3:
        assert ctl(plane, "watch", "--queue", "dev", "--job-set", "cli-test", "--timeout", "1") == 0
        out = capsys.readouterr().out
        succeeded = out.count("job_succeeded")
    stop.set()
    agent.join(timeout=5)
    assert succeeded == 3, out

    # lifecycle order visible in the final watch output
    assert out.index("submit_job") < out.index("job_run_leased") < out.index(
        "job_succeeded"
    )


def test_cli_trace_dump_and_summary(plane, capsys):
    """`armadactl trace`: Chrome trace-event JSON for REAL serving-plane
    cycles over the gRPC ExecutorAdmin verb -- the acceptance surface for
    the round-12 tracing tentpole.  Schema-checks every event the way
    Perfetto's importer does (name/ph/ts/pid/tid, dur on completes)."""
    import json

    # let the plane tick a few traced cycles
    deadline = time.time() + 30
    from armada_tpu.ops.trace import recorder

    while time.time() < deadline and not any(
        t.kind == "cycle" for t in recorder().last()
    ):
        time.sleep(0.05)
    assert ctl(plane, "trace") == 0
    doc = json.loads(capsys.readouterr().out)
    evs = doc["traceEvents"]
    assert evs, "a ticking plane must have recorded cycles"
    for ev in evs:
        assert {"name", "ph", "pid", "tid"} <= set(ev)
        if ev["ph"] == "X":
            assert ev["dur"] > 0 and "ts" in ev
    names = {e["name"] for e in evs}
    assert "scheduler_cycle" in names and "sync_state" in names

    assert ctl(plane, "trace", "--summary") == 0
    out = capsys.readouterr().out
    assert "trace " in out and "duration=" in out

    assert ctl(plane, "trace", "--raw") == 0
    raw = json.loads(capsys.readouterr().out)
    assert raw["traces"] and raw["traces"][-1]["root"]["name"] in (
        "scheduler_cycle",
    )


def test_cli_checkpoint_trigger_and_status(plane, capsys):
    """`armadactl checkpoint` + `--status`: the operator trigger for
    durable snapshots (scheduler/checkpoint.py) through the real gRPC
    surface."""
    import json
    import os

    assert ctl(plane, "checkpoint") == 0
    out = capsys.readouterr().out
    assert "checkpoint written" in out and "ckpt-" in out
    # the snapshot file exists and is the manager's newest
    loaded = plane.checkpoint_manager.load_newest()
    assert loaded is not None
    payload, path = loaded
    assert os.path.exists(path) and payload["db"]["consumer_positions"] is not None

    assert ctl(plane, "checkpoint", "--status") == 0
    status = json.loads(capsys.readouterr().out)
    assert status["epoch"] == 0
    assert status["checkpoint"]["snapshot"]["path"] == path
    assert status["checkpoint"]["count"] >= 1


def test_cli_quarantine_status_and_clear(plane, capsys):
    """`armadactl quarantine` + `--clear`: the operator's only way out of
    a round-verification quarantine (models/verify.py +
    scheduler/quarantine.py) through the real gRPC surface."""
    import json

    from armada_tpu.scheduler.quarantine import reset_device_quarantine

    dq = reset_device_quarantine(strikes=1)
    try:
        dq.record_strikes(["chip0"], "cli drill")
        assert ctl(plane, "quarantine") == 0
        block = json.loads(capsys.readouterr().out)
        assert "chip0" in block["quarantine"]["quarantined"]
        assert "failures_by_site" in block
        assert ctl(plane, "quarantine", "--clear") == 0
        assert "chip0" in capsys.readouterr().out
        assert dq.quarantined() == {}
        assert ctl(plane, "quarantine", "--clear") == 0
        assert "nothing to clear" in capsys.readouterr().out
    finally:
        reset_device_quarantine()


def test_cli_cancel_and_reprioritize(plane, tmp_path, capsys):
    ctl(plane, "queue", "create", "ops")
    sub = tmp_path / "job.yaml"
    sub.write_text(
        """
queue: ops
jobSetId: stuck
jobs:
  - count: 2
    resources: {cpu: "64", memory: "1"}   # unschedulably large
"""
    )
    ctl(plane, "submit", str(sub))
    capsys.readouterr()

    assert ctl(plane, "reprioritize", "--queue", "ops", "--job-set", "stuck", "--priority", "5") == 0
    assert ctl(plane, "cancel", "--queue", "ops", "--job-set", "stuck") == 0
    deadline = time.time() + 20
    cancelled = 0
    while time.time() < deadline and cancelled < 2:
        ctl(plane, "watch", "--queue", "ops", "--job-set", "stuck", "--timeout", "0.5")
        cancelled = capsys.readouterr().out.count("cancelled_job")
    assert cancelled == 2


def test_serve_restart_recovers_state(tmp_path, capsys):
    data = str(tmp_path / "data")
    p1 = start_control_plane(
        data,
        port=0,
        config=SchedulingConfig(shape_bucket=32),
        cycle_interval_s=0.05,
        schedule_interval_s=0.1,
    )
    try:
        assert main(["--url", f"127.0.0.1:{p1.port}", "queue", "create", "persist"]) == 0
        sub = tmp_path / "job.yaml"
        sub.write_text(
            "queue: persist\njobSetId: js\njobs:\n  - resources: {cpu: '1', memory: '1'}\n"
        )
        assert main(["--url", f"127.0.0.1:{p1.port}", "submit", str(sub)]) == 0
        time.sleep(0.5)
    finally:
        p1.stop()

    # second incarnation on the same data dir sees the queue AND the job
    p2 = start_control_plane(
        data,
        port=0,
        config=SchedulingConfig(shape_bucket=32),
        cycle_interval_s=0.05,
        schedule_interval_s=0.1,
    )
    try:
        assert main(["--url", f"127.0.0.1:{p2.port}", "queue", "describe", "persist"]) == 0
        out = capsys.readouterr().out
        assert "persist" in out
        rows, _ = p2.scheduler.db.fetch_job_updates(0, 0)
        assert len(rows) == 1
        # events replayed into the stream store exactly once
        events = p2.event_api.get_jobset_events("persist", "js")
        kinds = [
            ev.WhichOneof("event") for e in events for ev in e.sequence.events
        ]
        assert kinds.count("submit_job") == 1
    finally:
        p2.stop()


def test_version_verb(capsys):
    """armadactl version (the reference's version.go)."""
    from armada_tpu.cli.armadactl import main

    assert main(["version"]) == 0
    out = capsys.readouterr().out
    assert "armadactl-tpu version" in out and "Python version" in out
