"""Dump cycle traces as Chrome trace-event JSON (Perfetto-loadable).

Two modes, one exporter (ops/trace.chrome_trace -- the same function
`armadactl trace` uses, so there is exactly ONE Chrome-JSON writer):

* ``--from-json FILE``: convert a raw offset-form dump (the output of
  ``armadactl trace --raw``, or a saved ``dump()``) into Chrome JSON.
* no input: run a small synthetic traced steady cycle IN-PROCESS (scale
  knobs PJOBS/PNODES/PQUEUES/PBURST, defaults tiny) and dump its trace --
  the zero-infrastructure way to see the span timeline of this build.

Usage:
    python tools/trace_dump.py -o cycle.json            # synthetic capture
    python tools/trace_dump.py --from-json raw.json -o cycle.json
    armadactl trace --raw | python tools/trace_dump.py --from-json - -o c.json

Open the output at https://ui.perfetto.dev or chrome://tracing.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synthetic_capture(cycles: int = 3) -> dict:
    """Run a few traced steady cycles over a synthetic world; returns the
    raw dump (offset form)."""
    from armada_tpu.core.types import RunningJob
    from armada_tpu.models import decode_result, schedule_round
    from armada_tpu.models.incremental import IncrementalBuilder
    from armada_tpu.models.slab import DeviceDeltaCache
    from armada_tpu.models.synthetic import synthetic_world
    from armada_tpu.ops.trace import reset_recorder

    jobs = int(os.environ.get("PJOBS", 2_000))
    nodes = int(os.environ.get("PNODES", 200))
    queues = int(os.environ.get("PQUEUES", 8))
    burst = int(os.environ.get("PBURST", 100))
    config, nodes_l, queues_l, specs, running, spec_factory = synthetic_world(
        num_nodes=nodes,
        num_jobs=jobs,
        num_queues=queues,
        num_runs=nodes // 2,
        seed=7,
    )
    rec = reset_recorder()
    builder = IncrementalBuilder(config, "default", queues_l)
    builder.set_nodes(nodes_l)
    builder.submit_many(specs)
    for r in running:
        builder.lease(r)
    spec_of = {s.id: s for s in specs}
    devcache = DeviceDeltaCache()
    for i in range(cycles):
        with rec.cycle("steady_cycle", kind="cycle", n=i):
            bundle, ctx = builder.assemble_delta()
            dev = devcache.apply(bundle)
            with rec.span("kernel_dispatch"):
                result = schedule_round(
                    dev,
                    num_levels=len(ctx.ladder) + 2,
                    max_slots=ctx.max_slots,
                    slot_width=ctx.slot_width,
                )
            with rec.span("fetch_decode"):
                outcome = decode_result(result, ctx)
            with rec.span("apply", scheduled=len(outcome.scheduled)):
                builder.remove_many(outcome.scheduled.keys())
                leases = [
                    RunningJob(job=spec_of[jid], node_id=nid)
                    for jid, nid in outcome.scheduled.items()
                    if jid in spec_of
                ]
                builder.lease_many(leases)
            fresh = spec_factory(burst, 100.0 + i)
            for s in fresh:
                spec_of[s.id] = s
            builder.submit_many(fresh)  # carries its own trace span
    return rec.dump()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--from-json",
        default="",
        help="raw offset-form dump to convert ('-' = stdin); omit to run "
        "a synthetic traced capture in-process",
    )
    ap.add_argument("-o", "--out", default="", help="output file (default stdout)")
    ap.add_argument(
        "--cycles", type=int, default=3, help="synthetic cycles to capture"
    )
    args = ap.parse_args()

    if args.from_json:
        if args.from_json == "-":
            dump = json.load(sys.stdin)
        else:
            with open(args.from_json, "r", encoding="utf-8") as fh:
                dump = json.load(fh)
    else:
        dump = synthetic_capture(args.cycles)

    from armada_tpu.ops.trace import chrome_trace

    doc = chrome_trace(dump.get("traces", []))
    text = json.dumps(doc)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(
            f"wrote {len(dump.get('traces', []))} trace(s), "
            f"{len(doc['traceEvents'])} events to {args.out} "
            "(open in https://ui.perfetto.dev)",
            file=sys.stderr,
        )
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
