"""Scheduling models: the tensorised scheduling round.

`problem` builds dense device tensors from host job/node/queue objects;
`fair_scheduler` is the jitted round kernel -- the TPU-native replacement for the
reference's PreemptingQueueScheduler -> QueueScheduler -> GangScheduler -> NodeDb
pipeline (internal/scheduler/scheduling/*.go).
"""

from armada_tpu.models.problem import (
    SchedulingProblem,
    HostContext,
    build_problem,
    decode_result,
    RoundOutcome,
)
from armada_tpu.models.fair_scheduler import schedule_round, RoundResult


def run_scheduling_round(
    config,
    *,
    pool,
    nodes,
    queues,
    queued_jobs,
    running=(),
    collect_stats=True,
    bid_price_of=None,
    away_mode=False,
    global_tokens=None,
    queue_tokens=None,
    banned_nodes=None,
    queue_penalty=None,
):
    """Convenience host API: build the dense problem, run the jitted round on
    device, decode back to ids.  Equivalent of one SchedulingAlgo.Schedule call for
    one pool (scheduling_algo.go SchedulePool:574)."""
    import jax.numpy as jnp

    from armada_tpu.models.problem import queue_stats_from_result

    problem, ctx = build_problem(
        config,
        pool=pool,
        nodes=nodes,
        queues=queues,
        queued_jobs=queued_jobs,
        running=running,
        bid_price_of=bid_price_of,
        away_mode=away_mode,
        global_tokens=global_tokens,
        queue_tokens=queue_tokens,
        banned_nodes=banned_nodes,
        queue_penalty=queue_penalty,
    )
    device_problem = SchedulingProblem(*(jnp.asarray(a) for a in problem))
    result = schedule_round(
        device_problem,
        num_levels=len(ctx.ladder) + 2,
        max_slots=ctx.max_slots,
        slot_width=ctx.slot_width,
        # Static flag (not a tensor): the default compile carries none of the
        # alternate-ordering work.  Market pools keep bid ordering.
        prefer_large=bool(
            config.enable_prefer_large_job_ordering
            and not bool(problem.market)
        ),
    )
    outcome = decode_result(result, ctx)
    outcome.pool_totals = ctx.pool_total_atoms
    if collect_stats:
        # Extra device->host transfer + host-side DRF recompute: skipped when
        # neither metrics nor reports consume it.
        outcome.queue_stats = queue_stats_from_result(result, problem, ctx)
        if config.indicative_share_base_priorities:
            from armada_tpu.ops.fairness import theoretical_share

            # config parsing rejects non-positive priorities up front
            outcome.indicative_shares = {
                p: theoretical_share(problem.q_weight, problem.q_cds, float(p))
                for p in config.indicative_share_base_priorities
            }
    return outcome


__all__ = [
    "run_scheduling_round",
    "SchedulingProblem",
    "HostContext",
    "build_problem",
    "decode_result",
    "RoundOutcome",
    "schedule_round",
    "RoundResult",
]
