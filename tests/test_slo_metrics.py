"""ops/metrics (LogHistogram + registry) and scheduler/slo (SLORecorder).

The histogram's percentile math is pinned EXACTLY against an independent
vectorized numpy oracle: both sides map values through the same edge array
(the histogram via its streaming counts, the oracle via one vectorized
searchsorted + sort), so the assertion is float equality, not approx.
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from armada_tpu.ops.metrics import Counter, LogHistogram, MetricsRegistry, mono_now
from armada_tpu.scheduler.slo import SLORecorder, recorder, reset_recorder


def oracle_quantile(values: np.ndarray, hist: LogHistogram, q: float) -> float:
    """Independent numpy implementation of the histogram's rank-based
    percentile: bucket every value (vectorized), sort, take the bucket of
    the ceil(q*n)-th smallest sample, answer its upper edge."""
    idx = np.minimum(
        # lint: allow(searchsorted-dtype) -- f64 values into the f64 edges array; the oracle must not coerce
        np.searchsorted(hist.edges, values, side="left"),
        len(hist.edges) - 1,
    )
    order = np.sort(idx)
    rank = min(int(np.ceil(q * len(values))), len(values))
    return float(hist.edges[order[rank - 1]])


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_histogram_percentiles_match_numpy_oracle_exactly(seed):
    rng = np.random.default_rng(seed)
    # lognormal latencies spanning the bucket range + deliberate edge hits
    values = np.concatenate(
        [
            rng.lognormal(mean=-3.0, sigma=2.0, size=5000),
            np.array([1e-4, 1e-3, 0.5, 1.0, 9_999.0]),
        ]
    )
    h = LogHistogram("t")
    for v in values:
        h.record(v)
    assert h.count == len(values)
    assert h.vmin == float(values.min()) and h.vmax == float(values.max())
    assert h.total == pytest.approx(float(values.sum()), rel=1e-9)
    for q in (0.01, 0.25, 0.5, 0.9, 0.95, 0.99, 0.999, 1.0):
        assert h.quantile(q) == oracle_quantile(values, h, q), q


def test_histogram_quantile_is_within_resolution_of_true_percentile():
    rng = np.random.default_rng(7)
    values = rng.lognormal(mean=-1.0, sigma=1.0, size=20_000)
    h = LogHistogram("t")
    for v in values:
        h.record(v)
    for q in (0.5, 0.95, 0.99):
        true = float(np.quantile(values, q, method="inverted_cdf"))
        est = h.quantile(q)
        # upper-edge semantics: est >= true, within one growth factor
        assert true <= est <= true * 2 ** 0.125 * (1 + 1e-12)


def test_histogram_clamps_never_drops():
    h = LogHistogram("t", lo=1e-3, hi=10.0)
    for v in (0.0, 1e-9, 1e-3, 5.0, 10.0, 1e6):
        h.record(v)
    assert h.count == 6
    assert int(h.counts.sum()) == 6
    assert h.quantile(1.0) == float(h.edges[-1])  # overflow clamped
    assert h.quantile(0.0) == 0.0  # exact tracked min


def test_histogram_empty_and_reset():
    h = LogHistogram("t")
    assert h.quantile(0.5) is None
    assert h.snapshot() == {"count": 0}
    h.record(0.25)
    assert h.snapshot()["count"] == 1
    h.reset()
    assert h.snapshot() == {"count": 0}


def test_histogram_merge_equals_union():
    rng = np.random.default_rng(3)
    a_vals = rng.lognormal(size=500)
    b_vals = rng.lognormal(size=700)
    a, b, u = LogHistogram("a"), LogHistogram("b"), LogHistogram("u")
    for v in a_vals:
        a.record(v)
        u.record(v)
    for v in b_vals:
        b.record(v)
        u.record(v)
    a.merge(b)
    assert a.count == u.count
    assert np.array_equal(a.counts, u.counts)
    for q in (0.5, 0.99):
        assert a.quantile(q) == u.quantile(q)


def test_histogram_rejects_nan_and_negative_as_zero():
    h = LogHistogram("t")
    h.record(float("nan"))
    h.record(-5.0)
    assert h.count == 2
    assert h.vmax == 0.0


def test_registry_get_or_create_and_type_guard():
    reg = MetricsRegistry("test")
    h1 = reg.histogram("lat")
    h2 = reg.histogram("lat")
    assert h1 is h2
    reg.counter("n").inc(3)
    assert reg.snapshot()["n"] == 3
    with pytest.raises(TypeError):
        reg.gauge("lat")
    reg.reset()
    assert reg.snapshot()["n"] == 0


def test_mono_now_is_monotonic():
    a = mono_now()
    b = mono_now()
    assert b >= a


def test_slo_recorder_ttfl_and_ingest_lag_flow():
    rec = SLORecorder()
    t0 = mono_now() - 0.5  # submitted half a second ago
    rec.note_submitted(["j1", "j2", "j3"], t=t0)
    assert rec.snapshot()["jobs_submitted"] == 3
    rec.note_visible(["j1", "j2", "unknown"])
    snap = rec.snapshot()
    assert snap["ingest_visible_lag_s"]["count"] == 2
    assert snap["ingest_visible_lag_s"]["min_s"] >= 0.5
    rec.note_leased(["j1"])
    rec.note_leased(["j1"])  # second lease of the same job: no double count
    snap = rec.snapshot()
    assert snap["time_to_first_lease_s"]["count"] == 1
    assert snap["jobs_first_leased"] == 1
    # j2 cancelled before leasing; j3 terminal: both leave the maps
    rec.forget(["j2", "j3"])
    assert rec.pending_lease_count() == 0


def test_slo_recorder_tracking_is_bounded():
    rec = SLORecorder(track_cap=2)
    rec.note_submitted(["a", "b", "c", "d"])
    assert rec.pending_lease_count() == 2
    assert rec.snapshot()["tracking_overflow"] == 2
    assert rec.snapshot()["jobs_submitted"] == 4


def test_slo_recorder_cycle_split_by_degradation():
    rec = SLORecorder()
    rec.observe_cycle(0.1, degraded=False)
    rec.observe_cycle(2.0, degraded=True)
    snap = rec.snapshot()
    assert snap["cycle_latency_s"]["count"] == 1
    assert snap["cycle_latency_degraded_s"]["count"] == 1


def test_global_recorder_reset():
    reset_recorder()
    r1 = recorder()
    r1.note_submitted(["x"])
    assert recorder() is r1
    r2 = reset_recorder()
    assert r2 is not r1
    assert r2.pending_lease_count() == 0


def test_healthz_embeds_slo_block():
    from armada_tpu.core.health import HealthServer, StartupCompleteChecker

    srv = HealthServer(port=0)
    try:
        startup = StartupCompleteChecker()
        srv.checker.add(startup)
        startup.mark_complete()
        rec = SLORecorder()
        rec.observe_cycle(0.2, degraded=False)
        srv.slo_status = rec.snapshot
        body = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5
            ).read()
        )
        assert body["healthy"] is True
        assert body["slo"]["cycle_latency_s"]["count"] == 1
    finally:
        srv.stop()


def test_healthz_embeds_pools_block():
    """Round 17: the pool-parallel serving scoreboard rides /healthz as
    the `pools` block (serve wires pool_serving_stats().snapshot)."""
    from armada_tpu.core.health import HealthServer, StartupCompleteChecker
    from armada_tpu.scheduler.pool_serving import (
        pool_serving_stats,
        reset_pool_serving_stats,
    )

    reset_pool_serving_stats()
    pool_serving_stats().record_cycle(
        parallel=True,
        armed=True,
        pool_round_s={"gpu": 0.01, "cpu": 0.02},
        stacked_launches=1,
        stacked_pools=2,
        overlap_ratio=1.4,
    )
    srv = HealthServer(port=0)
    try:
        startup = StartupCompleteChecker()
        srv.checker.add(startup)
        startup.mark_complete()
        srv.pools_status = lambda: pool_serving_stats().snapshot()
        body = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5
            ).read()
        )
        assert body["pools"]["parallel_cycles"] == 1
        assert body["pools"]["stacked_launches"] == 1
        assert body["pools"]["last_overlap_ratio"] == 1.4
        assert body["pools"]["last_round_s"]["gpu"] == 0.01
    finally:
        srv.stop()
        reset_pool_serving_stats()


def test_scheduler_metrics_export_slo_gauges():
    from prometheus_client import CollectorRegistry

    from armada_tpu.scheduler.metrics import SchedulerMetrics

    reg = CollectorRegistry()
    m = SchedulerMetrics(registry=reg)
    rec = SLORecorder()
    rec.observe_cycle(0.25, degraded=False)
    rec.note_submitted(["j"], t=mono_now() - 1.0)
    rec.note_leased(["j"])
    m.observe_slo(rec.snapshot())
    sample = reg.get_sample_value(
        "armada_scheduler_slo_latency_seconds",
        {"metric": "cycle_latency_s", "quantile": "p50"},
    )
    assert sample is not None and sample > 0
    assert (
        reg.get_sample_value(
            "armada_scheduler_slo_observations",
            {"metric": "time_to_first_lease_s"},
        )
        == 1.0
    )


def test_slo_per_pool_round_histograms():
    """Round 17: per-pool round latency rides its own histograms (the
    slow-tenant signal), with the degraded-attribution rule applied per
    ROUND; snapshot exposes them under "pools", reset clears them."""
    rec = SLORecorder()
    rec.observe_pool_round("gpu", 0.2)
    rec.observe_pool_round("gpu", 0.4, degraded=True)
    rec.observe_pool_round("cpu", 0.05)
    snap = rec.snapshot()
    assert snap["pools"]["gpu"]["count"] == 2
    assert snap["pools"]["gpu"]["degraded_rounds"] == 1
    assert snap["pools"]["cpu"]["degraded_rounds"] == 0
    assert snap["pools"]["cpu"]["p50_s"] <= snap["pools"]["gpu"]["p50_s"]
    rec.reset()
    assert "pools" not in rec.snapshot()


def test_scheduler_metrics_export_pool_cycle_gauges_with_stale_removal():
    """armada_scheduler_pool_cycle_seconds{pool,quantile} exports the
    per-pool histograms; a pool the recorder stops reporting is removed
    (the stale-label discipline every labelled gauge here follows)."""
    from prometheus_client import CollectorRegistry

    from armada_tpu.scheduler.metrics import SchedulerMetrics

    reg = CollectorRegistry()
    m = SchedulerMetrics(registry=reg)
    rec = SLORecorder()
    rec.observe_pool_round("gpu", 0.2)
    m.observe_slo(rec.snapshot())
    assert (
        reg.get_sample_value(
            "armada_scheduler_pool_cycle_seconds",
            {"pool": "gpu", "quantile": "p50"},
        )
        is not None
    )
    rec2 = SLORecorder()
    rec2.observe_pool_round("cpu", 0.1)
    m.observe_slo(rec2.snapshot())
    assert (
        reg.get_sample_value(
            "armada_scheduler_pool_cycle_seconds",
            {"pool": "gpu", "quantile": "p50"},
        )
        is None
    ), "stale pool series must be removed"
    assert (
        reg.get_sample_value(
            "armada_scheduler_pool_cycle_seconds",
            {"pool": "cpu", "quantile": "p50"},
        )
        is not None
    )
