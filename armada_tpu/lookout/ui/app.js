// Lookout SPA entry point: jobs table, grouping with per-state meters,
// drilldown (queue -> jobsets -> jobs -> details -> logs), URL-state
// routing, saved views, identity chip.  Capability map of the reference's
// React lookout UI (internal/lookoutui/src/App.tsx) over the same JSON API.
import { $, esc, fmtT, fmtDur, fmtCpu, fmtBytes, dark, meterHTML, chipsHTML, stateCell } from "./util.js";
import { j, postAction, AuthRequired } from "./api.js";
import { renderWhoami } from "./auth.js";
import { applyHash, syncHash } from "./router.js";
import { loadViews, wireViews } from "./views.js";
import { openDetails } from "./details.js";

const state = {
  skip: 0, take: 50, orderField: "submitted", orderDir: "DESC",
  // drilldown trail: [{field, value, group}] -- group is the grouping that
  // was active when the crumb was pushed, restored when the crumb is popped
  drill: [],
};
let contentSeq = 0, overviewSeq = 0;  // drop stale responses

function filterQS() {
  const p = new URLSearchParams();
  if ($("f-queue").value) p.set("queue", $("f-queue").value);
  if ($("f-jobset").value) p.set("jobset", $("f-jobset").value);
  if ($("f-state").value) p.set("state", $("f-state").value);
  const ann = $("f-ann").value.trim();
  if (ann && ann.includes("=")) {
    const i = ann.indexOf("=");
    p.set("ann." + ann.slice(0, i).trim(), ann.slice(i + 1).trim() || "*");
  }
  return p;
}

async function loadOverview() {
  const my = ++overviewSeq;
  const d = await j("/api/overview");
  if (my !== overviewSeq) return;  // a newer request superseded this one
  const total = Object.values(d.states).reduce((a, b) => a + b, 0);
  $("overview").innerHTML = meterHTML(d.states, total);
  $("chips").innerHTML = chipsHTML(d.states);
  $("total").textContent = total + " jobs";
}

// Jobs-table column registry (the reference UI's column picker): key ->
// {label, sort field (if server-sortable), render}.  Visibility persists in
// localStorage and survives reloads like the reference's column menu.
const COLS = {
  job_id:   {label: "job",       o: "job_id",   r: (x) => esc(x.job_id)},
  queue:    {label: "queue",     o: "queue",    r: (x) => esc(x.queue)},
  jobset:   {label: "jobset",    o: "jobset",   r: (x) => esc(x.jobset)},
  state:    {label: "state",     o: "state",    r: (x) => stateCell(x.state)},
  priority: {label: "priority",  o: "priority", num: 1, r: (x) => x.priority},
  priority_class: {label: "priority class", r: (x) => esc(x.priority_class || "—")},
  cpu:      {label: "cpu",    num: 1, r: (x) => fmtCpu(x.cpu_milli)},
  memory:   {label: "memory", num: 1, r: (x) => fmtBytes(x.memory)},
  gpu:      {label: "gpu",    num: 1, r: (x) => fmtCpu(x.gpu)},
  gang:     {label: "gang",   r: (x) => esc(x.gang_id || "—")},
  submitted:{label: "submitted", o: "submitted", r: (x) => fmtT(x.submitted_ns)},
  age:      {label: "time in state", r: (x) =>
              fmtDur(Date.now() * 1e6 - (x.last_transition_ns || x.submitted_ns))},
  node:     {label: "node", r: (x) => esc(x.node || "—")},
};
const DEFAULT_COLS = ["job_id", "queue", "jobset", "state", "priority", "submitted", "node"];
function visibleCols() {
  if (sessionCols) return sessionCols;
  try {
    const v = JSON.parse(localStorage.getItem("lookout-cols"));
    if (Array.isArray(v) && v.length && v.every((k) => COLS[k])) return v;
  } catch (e) { /* fall through */ }
  return DEFAULT_COLS;
}
let sessionCols = null;  // fallback when storage is unavailable
function setVisibleCols(keys) {
  const v = Object.keys(COLS).filter((k) => keys.includes(k));
  sessionCols = v;
  try { localStorage.setItem("lookout-cols", JSON.stringify(v)); }
  catch (e) { /* storage disabled: picker still works for this session */ }
}
function wireColPicker() {
  const btn = $("cols-btn");
  if (!btn) return;
  btn.onclick = () => {
    const menu = $("cols-menu");
    if (menu.classList.toggle("open")) {
      const vis = visibleCols();
      menu.innerHTML = Object.entries(COLS).map(([k, c]) =>
        `<label><input type="checkbox" data-c="${k}"
          ${vis.includes(k) ? "checked" : ""}> ${esc(c.label)}</label>`).join("");
      for (const cb of menu.querySelectorAll("input")) {
        cb.onchange = () => {
          const keys = [...menu.querySelectorAll("input:checked")]
            .map((x) => x.dataset.c);
          if (!keys.length) { cb.checked = true; return; }  // never zero columns
          setVisibleCols(keys);
          refresh();
        };
      }
    }
  };
}

async function loadContent() {
  const my = ++contentSeq;
  const group = $("f-group").value;
  if (group === "annotation" && !$("f-groupkey").value.trim()) {
    $("content").innerHTML = '<div class="empty">enter an annotation key to group by</div>';
    $("pager").innerHTML = "";
    return;
  }
  if (group) {
    const keyQ = group === "annotation"
      ? `&key=${encodeURIComponent($("f-groupkey").value.trim())}` : "";
    const d = await j(`/api/groups?by=${group}&take=500${keyQ}&` + filterQS());
    if (my !== contentSeq) return;
    $("pager").innerHTML = "";
    if (!d.groups.length) { $("content").innerHTML = '<div class="empty">nothing matches</div>'; return; }
    const note = d.truncated
      ? `<div class="empty">showing the ${d.groups.length} largest groups — refine the filters to see the rest</div>`
      : "";
    // Jobset mass actions (CancelJobSetsDialog / ReprioritizeJobSetsDialog
    // parity) need an unambiguous queue: offered whenever a queue filter is
    // set (drilldown, hand-typed, or a saved view -- the server validates
    // the exact queue name either way).
    const qname = $("f-queue").value.trim();
    const jsActions = group === "jobset" && qname;
    $("content").innerHTML = `<table><thead><tr><th>${esc(group)}</th>
      <th class="num">jobs</th><th>states</th>${jsActions ? "<th></th>" : ""}</tr></thead><tbody>` +
      d.groups.map((g) => {
        const total = g.count;
        return `<tr data-group="${esc(g.group)}"><td>${esc(g.group)}</td>
          <td class="num">${g.count}</td>
          <td><div class="mini">${meterHTML(g.states, total)}</div></td>
          ${jsActions ? `<td><button class="logbtn js-cancel" data-js="${esc(g.group)}">cancel set</button>
            <button class="logbtn js-reprio" data-js="${esc(g.group)}">reprioritise…</button></td>` : ""}</tr>`;
      }).join("") + "</tbody></table>" + note;
    if (jsActions) {
      const doAct = async (btn, path, body) => {
        // disable the row's buttons until the refresh: the lookout rows
        // lag the scheduler cycle, and a still-live button invites a
        // duplicate jobset-wide action (same guard as details.js act())
        const row = btn.closest("tr");
        for (const b of row.querySelectorAll("button")) b.disabled = true;
        const err = await postAction(path, body);
        if (err !== null) {
          alert(`action failed: ${err}`);
          for (const b of row.querySelectorAll("button")) b.disabled = false;
          return;
        }
        setTimeout(() => refresh(), 2000);
      };
      for (const b of $("content").querySelectorAll(".js-cancel"))
        b.onclick = (ev) => {
          ev.stopPropagation();
          if (!confirm(`cancel ALL jobs of jobset "${b.dataset.js}"?`)) return;
          doAct(b, "/api/jobsets/cancel",
                {queue: qname, jobset: b.dataset.js});
        };
      for (const b of $("content").querySelectorAll(".js-reprio"))
        b.onclick = (ev) => {
          ev.stopPropagation();
          const p = prompt(`new priority for every job of "${b.dataset.js}":`);
          if (p === null || p === "" || isNaN(+p)) return;
          doAct(b, "/api/jobsets/reprioritize",
                {queue: qname, jobset: b.dataset.js, priority: +p});
        };
    }
    for (const tr of $("content").querySelectorAll("tr[data-group]")) {
      tr.onclick = () => {
        const v = tr.dataset.group;
        if (group === "state") { $("f-state").value = v; $("f-group").value = ""; }
        else if (group === "annotation") {
          $("f-ann").value = $("f-groupkey").value.trim() + "=" + v;
          $("f-group").value = "";
        } else if (group === "queue") {
          // drill: queue -> its jobsets -> job list
          state.drill.push({field: "f-queue", value: v, group});
          $("f-queue").value = v;
          $("f-group").value = "jobset";
        } else {
          state.drill.push({field: "f-jobset", value: v, group});
          $("f-jobset").value = v;
          $("f-group").value = "";
        }
        state.skip = 0;
        refresh(true);  // drill steps push history: back button walks out
      };
    }
    return;
  }
  const p = filterQS();
  p.set("skip", state.skip); p.set("take", state.take);
  p.set("order", state.orderField); p.set("dir", state.orderDir);
  const d = await j("/api/jobs?" + p);
  if (my !== contentSeq) return;
  if (!d.jobs.length && d.total > 0 && state.skip > 0) {
    // the filtered total shrank under our page cursor: snap back
    state.skip = Math.max(0, (Math.ceil(d.total / state.take) - 1) * state.take);
    return loadContent();
  }
  if (!d.jobs.length) { $("content").innerHTML = '<div class="empty">nothing matches</div>'; $("pager").innerHTML = ""; return; }
  const arrow = (f) => state.orderField === f ? (state.orderDir === "ASC" ? " ↑" : " ↓") : "";
  const cols = visibleCols();
  $("content").innerHTML = `<table><thead><tr>` +
    cols.map((k) => {
      const c = COLS[k];
      return `<th ${c.num ? 'class="num"' : ""} ${c.o ? `data-o="${c.o}"` : ""}>` +
        `${esc(c.label)}${c.o ? arrow(c.o) : ""}</th>`;
    }).join("") + "</tr></thead><tbody>" +
    d.jobs.map((r) => `<tr data-id="${esc(r.job_id)}">` +
      cols.map((k) =>
        `<td ${COLS[k].num ? 'class="num"' : ""}>${COLS[k].r(r)}</td>`
      ).join("") + "</tr>").join("") +
    "</tbody></table>";
  for (const th of $("content").querySelectorAll("th[data-o]")) {
    th.onclick = () => {
      if (state.orderField === th.dataset.o) state.orderDir = state.orderDir === "ASC" ? "DESC" : "ASC";
      else { state.orderField = th.dataset.o; state.orderDir = "ASC"; }
      refresh();
    };
  }
  for (const tr of $("content").querySelectorAll("tr[data-id]"))
    tr.onclick = () => openDetails(tr.dataset.id);
  const page = Math.floor(state.skip / state.take) + 1;
  const pages = Math.max(1, Math.ceil(d.total / state.take));
  $("pager").innerHTML = `<button id="prev" ${state.skip ? "" : "disabled"}>‹ prev</button>
    <span>page ${page} / ${pages} (${d.total} jobs)</span>
    <button id="next" ${state.skip + state.take < d.total ? "" : "disabled"}>next ›</button>`;
  if ($("prev")) $("prev").onclick = () => { state.skip = Math.max(0, state.skip - state.take); refresh(); };
  if ($("next")) $("next").onclick = () => { state.skip += state.take; refresh(); };
}

function renderCrumbs() {
  $("crumbs").innerHTML = state.drill.map((c, i) =>
    `<span class="crumb" data-i="${i}" title="back to this level">` +
    `${esc(c.field === "f-queue" ? "queue" : "jobset")}: ${esc(c.value)} ✕</span>`
  ).join("");
  for (const el of $("crumbs").querySelectorAll(".crumb")) {
    el.onclick = () => {
      const i = +el.dataset.i;
      // pop this crumb and everything after it; restore its grouping level
      const popped = state.drill[i];
      for (const c of state.drill.slice(i)) $(c.field).value = "";
      state.drill = state.drill.slice(0, i);
      $("f-group").value = popped.group;
      state.skip = 0;
      refresh(true);
    };
  }
}

function refresh(push) {
  syncHash(state, !!push);
  renderCrumbs();
  loadOverview().catch(swallowAuthRedirect);
  loadContent().catch(swallowAuthRedirect);
}
function swallowAuthRedirect(e) {
  if (!(e instanceof AuthRequired)) throw e;
}

$("refresh").onclick = () => refresh();
for (const id of ["f-queue", "f-jobset", "f-state", "f-group", "f-ann", "f-groupkey"])
  $(id).addEventListener("change", () => {
    state.skip = 0;
    // manual edits invalidate any drilldown crumb they contradict
    state.drill = state.drill.filter((c) => $(c.field).value === c.value);
    refresh();
  });
$("f-group").addEventListener("change", () => {
  $("f-groupkey").style.display =
    $("f-group").value === "annotation" ? "" : "none";
});
$("theme").onclick = () => {
  const r = document.documentElement;
  r.dataset.theme = dark() ? "light" : "dark";
  refresh();
};
addEventListener("popstate", () => { applyHash(state); refresh(); });
setInterval(() => { if ($("auto").checked && !$("details").classList.contains("open")) refresh(); }, 3000);

wireViews(state, refresh);
wireColPicker();
loadViews();
renderWhoami();
applyHash(state);
refresh();
