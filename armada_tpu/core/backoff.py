"""Bounded exponential backoff with full jitter.

Every retry loop that talks to a peer which may be DOWN (the eventlog
follower tailing a dead leader, the ingestion pipeline replaying a batch
against a restarting database, the pgwire adapter reconnecting) must not
spin hot OR retry in lockstep: fixed sleeps synchronize every waiter onto
the recovering peer at the same instant.  This is the AWS-style
full-jitter schedule -- delay_n = uniform(0, min(cap, base * 2**n)) -- with
a floor so a jittered delay never degenerates to a busy loop.
"""

from __future__ import annotations

import random
import time
from typing import Optional


class Backoff:
    """One retry loop's schedule; not thread-safe (one loop, one instance).

    ``max_attempts`` / ``deadline_s`` bound the schedule: ``exhausted()``
    turns True once the loop has drawn ``max_attempts`` delays or has been
    retrying for ``deadline_s`` seconds (measured from the first
    ``next_delay`` after a ``reset``).  Both default to None -- unbounded,
    the behavior every pre-existing call site keeps.  A bounded loop
    decides what exhaustion MEANS (the ingest plane escalates to poison
    isolation, ingest/dlq.py); the schedule only reports it.
    """

    def __init__(
        self,
        base_s: float = 0.2,
        cap_s: float = 30.0,
        floor_s: float = 0.05,
        rng: Optional[random.Random] = None,
        max_attempts: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ):
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.floor_s = min(float(floor_s), float(base_s))
        self.attempts = 0
        self.max_attempts = max_attempts
        self.deadline_s = deadline_s
        self._started_at: Optional[float] = None
        self._rng = rng or random.Random()

    def reset(self) -> None:
        self.attempts = 0
        self._started_at = None

    def exhausted(self) -> bool:
        """True once the bounded budget is spent: ``max_attempts`` delays
        drawn, or ``deadline_s`` elapsed since the first post-reset delay.
        Always False for the default unbounded schedule."""
        if self.max_attempts is not None and self.attempts >= self.max_attempts:
            return True
        if (
            self.deadline_s is not None
            and self._started_at is not None
            and time.monotonic() - self._started_at >= self.deadline_s
        ):
            return True
        return False

    def next_delay(self) -> float:
        """The delay before the NEXT attempt; advances the attempt count.
        Callers log the delay and then sleep/wait it themselves (the log
        line must precede the wait it describes)."""
        if self._started_at is None:
            self._started_at = time.monotonic()
        # exponent clamped: 2.0**1024 overflows float, and a sustained
        # outage (a down DB for an hour) really does reach four-digit
        # attempt counts -- the cap dominates long before 2**60 anyway
        ceiling = min(self.cap_s, self.base_s * (2.0 ** min(self.attempts, 60)))
        self.attempts += 1
        return max(self.floor_s, self._rng.uniform(0.0, ceiling))
