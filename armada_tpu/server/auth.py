"""Authorization: principals, global permissions, per-queue ACLs.

Equivalent of the reference's `internal/common/auth/authorization.go`
(ActionAuthorizer, principal groups, per-queue ACLs) plus the permission
vocabulary of internal/server/permissions/permissions.go.  Authentication
itself (OIDC/basic/kerberos) is out of scope for an in-process control plane;
principals arrive pre-authenticated from the transport layer.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from armada_tpu.server.queues import QueueRecord


class Permission(enum.Enum):
    SUBMIT_ANY_JOBS = "submit_any_jobs"
    CANCEL_ANY_JOBS = "cancel_any_jobs"
    PREEMPT_ANY_JOBS = "preempt_any_jobs"
    REPRIORITIZE_ANY_JOBS = "reprioritize_any_jobs"
    CREATE_QUEUE = "create_queue"
    DELETE_QUEUE = "delete_queue"
    CORDON_NODES = "cordon_nodes"
    WATCH_ALL_EVENTS = "watch_all_events"
    # Executor-level cordon/settings (reference permissions.UpdateExecutorSettings)
    UPDATE_EXECUTOR_SETTINGS = "update_executor_settings"


@dataclasses.dataclass(frozen=True)
class Principal:
    name: str = "anonymous"
    groups: tuple[str, ...] = ()
    # Global permissions granted by the operator's config.
    permissions: frozenset = frozenset()

    def is_member_of(self, group: str) -> bool:
        return group in self.groups


EVERYONE = "everyone"


class AuthorizationError(Exception):
    pass


class ActionAuthorizer:
    """Global permission OR queue-ownership check (authorization.go)."""

    def __init__(self, open_by_default: bool = True):
        # open_by_default mirrors the reference's anonymous-auth dev mode.
        self._open = open_by_default

    def authorize_action(self, principal: Principal, permission: Permission) -> None:
        if self._open or permission in principal.permissions:
            return
        raise AuthorizationError(
            f"{principal.name} lacks permission {permission.value}"
        )

    def authorize_queue_action(
        self,
        principal: Principal,
        queue: Optional[QueueRecord],
        permission: Permission,
    ) -> None:
        """Allowed if globally permitted, or the principal owns / is grouped
        into the queue (per-queue ACLs)."""
        if self._open or permission in principal.permissions:
            return
        if queue is not None:
            if principal.name and principal.name in queue.owners:
                return
            if any(
                g == EVERYONE or principal.is_member_of(g) for g in queue.groups
            ):
                return
        raise AuthorizationError(
            f"{principal.name} may not {permission.value} on queue "
            f"{queue.name if queue else '<unknown>'}"
        )
