"""SubmitServer: the client-facing mutation API.

Equivalent of the reference's Submit server (internal/server/submit/
submit.go:32-42): every verb authorizes, validates, dedups (submission only),
converts to events and publishes to the log -- the server never writes
job state anywhere else; all databases catch up via ingestion.

Verbs (submit.go): SubmitJobs:72, CancelJobs:155, PreemptJobs:202,
ReprioritizeJobs:251, CancelJobSet:316, queue CRUD passthrough:431-455.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Callable, Mapping, Optional, Sequence

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import (
    NODE_TYPE_SCORES_ANNOTATION,
    JobSpec,
    Toleration,
    parse_node_type_scores,
)
from armada_tpu.eventlog.publisher import Publisher
from armada_tpu.events import events_pb2 as pb
from armada_tpu.events.convert import job_spec_to_proto
from armada_tpu.ingest.schedulerdb import SchedulerDb
from armada_tpu.server.auth import ActionAuthorizer, Permission, Principal
from armada_tpu.server.queues import QueueRepository
from armada_tpu.server.validation import ValidationError, validate_submission


class SubmitError(ValueError):
    pass


# Re-exported for the gRPC layer; the canonical definition sits on the
# Publisher (the single gated choke point for every append path).
from armada_tpu.eventlog.publisher import NotLeader  # noqa: E402,F401


@dataclasses.dataclass(frozen=True)
class JobSubmitItem:
    """One job in a submission request (api.JobSubmitRequestItem)."""

    resources: Mapping[str, "str | int | float"]
    priority: int = 0
    priority_class: str = ""
    client_id: str = ""  # dedup id (submit/deduplication.go)
    node_selector: Mapping[str, str] = dataclasses.field(default_factory=dict)
    tolerations: tuple[Toleration, ...] = ()
    gang_id: str = ""
    gang_cardinality: int = 1
    gang_node_uniformity_label: str = ""
    pools: tuple[str, ...] = ()
    price_band: str = ""
    namespace: str = "default"
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    # Typed network objects (submit.proto ingress:9 / services:10), NOT
    # annotation-smuggled: validated at submit, materialised by executors.
    services: tuple = ()
    ingress: tuple = ()


def _new_job_id() -> str:
    # ULID-ish: time-prefixed so ids sort by submission within a process.
    return f"{int(time.time() * 1e3):013x}-{uuid.uuid4().hex[:12]}"


class SubmitServer:
    def __init__(
        self,
        db: SchedulerDb,
        publisher: Publisher,
        queues: QueueRepository,
        config: Optional[SchedulingConfig] = None,
        authorizer: Optional[ActionAuthorizer] = None,
        clock: Callable[[], float] = time.time,
        job_id_factory: Callable[[], str] = _new_job_id,
        write_gate: Optional[Callable[[], Optional[str]]] = None,
    ):
        """write_gate: replicated deployments only -- returns None when this
        replica may write (it holds the log of record) or the leader's
        address ("" = unknown) when it must not; every publishing verb
        checks it (a follower appending locally would fork the log its
        replicator is tailing)."""
        self._db = db
        self._publisher = publisher
        self._queues = queues
        self._config = config or SchedulingConfig()
        self._auth = authorizer or ActionAuthorizer()
        self._clock = clock
        self._job_id = job_id_factory
        self._write_gate = write_gate

    # --- helpers ------------------------------------------------------------

    def _queue_or_raise(self, queue: str):
        record = self._queues.get(queue)
        if record is None:
            raise SubmitError(f"queue {queue!r} does not exist")
        return record

    def _check_writable(self) -> None:
        if self._write_gate is not None:
            leader = self._write_gate()
            if leader is not None:
                raise NotLeader(leader)

    def _publish(self, queue: str, jobset: str, events: list, user: str) -> None:
        self._check_writable()
        self._publisher.publish(
            [
                pb.EventSequence(
                    queue=queue, jobset=jobset, user_id=user, events=events
                )
            ]
        )

    # --- SubmitJobs (submit.go:72) ------------------------------------------

    def submit_jobs(
        self,
        queue: str,
        jobset: str,
        items: Sequence[JobSubmitItem],
        principal: Principal = Principal(),
    ) -> list[str]:
        """Returns the job id per item (the original id for deduped items)."""
        self._check_writable()
        record = self._queue_or_raise(queue)
        self._auth.authorize_queue_action(
            principal, record, Permission.SUBMIT_ANY_JOBS
        )
        if not jobset:
            raise SubmitError("jobset must be non-empty")
        try:
            validate_submission(items, self._config)
        except ValidationError as e:
            raise SubmitError(str(e)) from None

        # Dedup by client id (deduplication.go GetOriginalJobIds).
        dedup_keys = {
            item.client_id: f"{queue}:{item.client_id}"
            for item in items
            if item.client_id
        }
        existing = self._db.lookup_dedup(list(dedup_keys.values()))

        now = self._clock()
        now_ns = int(now * 1e9)
        factory = self._config.resource_list_factory()
        events: list[pb.Event] = []
        job_ids: list[str] = []
        new_ids: list[str] = []
        new_dedup: dict[str, str] = {}
        for item in items:
            if item.client_id:
                key = dedup_keys[item.client_id]
                if key in existing:
                    job_ids.append(existing[key])
                    continue
            job_id = self._job_id()
            job_ids.append(job_id)
            new_ids.append(job_id)
            if item.client_id:
                new_dedup[dedup_keys[item.client_id]] = job_id
            spec = JobSpec(
                id=job_id,
                queue=queue,
                jobset=jobset,
                priority_class=item.priority_class,
                priority=item.priority,
                submit_time=now,
                resources=factory.from_mapping(item.resources),
                node_selector=dict(item.node_selector),
                tolerations=tuple(item.tolerations),
                gang_id=item.gang_id,
                gang_cardinality=item.gang_cardinality,
                gang_node_uniformity_label=item.gang_node_uniformity_label,
                pools=tuple(item.pools),
                price_band=item.price_band,
                services=tuple(item.services),
                ingress=tuple(item.ingress),
                # already validated; the typed field is what the events
                # proto and the scheduling key carry (the annotation stays
                # a pod-payload passthrough)
                node_type_scores=parse_node_type_scores(
                    dict(item.annotations).get(NODE_TYPE_SCORES_ANNOTATION, "")
                ),
            )
            msg = job_spec_to_proto(spec)
            msg.annotations.update(dict(item.annotations))
            msg.labels.update(dict(item.labels))
            msg.namespace = item.namespace
            events.append(
                pb.Event(
                    created_ns=now_ns,
                    submit_job=pb.SubmitJob(
                        job_id=job_id, spec=msg, client_id=item.client_id
                    ),
                )
            )

        if events:
            self._publish(queue, jobset, events, principal.name)
            # SLO clock start: submit ACCEPTED (publish succeeded).  Only
            # genuinely-new ids -- a deduped re-submit is not a new arrival
            # and must not reset its original's time-to-first-lease.
            from armada_tpu.scheduler.slo import recorder

            recorder().note_submitted(new_ids)
        if new_dedup:
            self._db.store_dedup(new_dedup)
        return job_ids

    # --- CancelJobs (submit.go:155) -----------------------------------------

    def cancel_jobs(
        self,
        queue: str,
        jobset: str,
        job_ids: Sequence[str],
        reason: str = "",
        principal: Principal = Principal(),
    ) -> None:
        self._check_writable()
        record = self._queue_or_raise(queue)
        self._auth.authorize_queue_action(
            principal, record, Permission.CANCEL_ANY_JOBS
        )
        if not job_ids:
            raise SubmitError("no job ids given")
        now_ns = int(self._clock() * 1e9)
        self._publish(
            queue,
            jobset,
            [
                pb.Event(
                    created_ns=now_ns,
                    cancel_job=pb.CancelJob(job_id=jid, reason=reason),
                )
                for jid in job_ids
            ],
            principal.name,
        )

    # --- CancelJobSet (submit.go:316) ---------------------------------------

    def cancel_jobset(
        self,
        queue: str,
        jobset: str,
        states: Sequence[str] = (),
        reason: str = "",
        principal: Principal = Principal(),
    ) -> None:
        self._check_writable()
        record = self._queue_or_raise(queue)
        self._auth.authorize_queue_action(
            principal, record, Permission.CANCEL_ANY_JOBS
        )
        for s in states:
            if s not in ("queued", "leased"):
                raise SubmitError(f"invalid jobset-cancel state {s!r}")
        now_ns = int(self._clock() * 1e9)
        self._publish(
            queue,
            jobset,
            [
                pb.Event(
                    created_ns=now_ns,
                    cancel_job_set=pb.CancelJobSet(
                        reason=reason, states=list(states)
                    ),
                )
            ],
            principal.name,
        )

    # --- PreemptJobs (submit.go:202) ----------------------------------------

    def preempt_jobs(
        self,
        queue: str,
        jobset: str,
        job_ids: Sequence[str],
        reason: str = "",
        principal: Principal = Principal(),
    ) -> None:
        self._check_writable()
        record = self._queue_or_raise(queue)
        self._auth.authorize_queue_action(
            principal, record, Permission.PREEMPT_ANY_JOBS
        )
        if not job_ids:
            raise SubmitError("no job ids given")
        now_ns = int(self._clock() * 1e9)
        self._publish(
            queue,
            jobset,
            [
                pb.Event(
                    created_ns=now_ns,
                    preempt_job=pb.PreemptJob(job_id=jid, reason=reason),
                )
                for jid in job_ids
            ],
            principal.name,
        )

    # --- ReprioritizeJobs (submit.go:251) -----------------------------------

    def reprioritize_jobs(
        self,
        queue: str,
        jobset: str,
        priority: int,
        job_ids: Sequence[str] = (),
        principal: Principal = Principal(),
    ) -> None:
        """Empty job_ids reprioritises the whole jobset."""
        self._check_writable()
        record = self._queue_or_raise(queue)
        self._auth.authorize_queue_action(
            principal, record, Permission.REPRIORITIZE_ANY_JOBS
        )
        if priority < 0:
            raise SubmitError("priority must be >= 0")
        now_ns = int(self._clock() * 1e9)
        if job_ids:
            events = [
                pb.Event(
                    created_ns=now_ns,
                    reprioritise_job=pb.ReprioritiseJob(
                        job_id=jid, priority=priority
                    ),
                )
                for jid in job_ids
            ]
        else:
            events = [
                pb.Event(
                    created_ns=now_ns,
                    reprioritise_job_set=pb.ReprioritiseJobSet(
                        priority=priority
                    ),
                )
            ]
        self._publish(queue, jobset, events, principal.name)

    # --- queue CRUD (submit.go:431-455) -------------------------------------

    def create_queue(self, record, principal: Principal = Principal()) -> None:
        self._auth.authorize_action(principal, Permission.CREATE_QUEUE)
        self._check_writable()
        if record.name.startswith("armada-"):
            # "armada-*" is reserved for system streams (e.g. the
            # armada-metrics cycle-metrics stream): user traffic must never
            # interleave with scheduler telemetry.
            raise ValueError(
                f"queue name {record.name!r} is reserved (armada- prefix)"
            )
        self._queues.create(record)

    def update_queue(self, record, principal: Principal = Principal()) -> None:
        self._auth.authorize_action(principal, Permission.CREATE_QUEUE)
        self._check_writable()
        self._queues.update(record)

    def delete_queue(self, name: str, principal: Principal = Principal()) -> None:
        self._auth.authorize_action(principal, Permission.DELETE_QUEUE)
        self._check_writable()
        self._queues.delete(name)

    def get_queue(self, name: str):
        return self._queues.get(name)

    def list_queues(self):
        return self._queues.list()
