"""Opt-in real-cluster e2e (VERDICT r3 missing #6): drive the kubernetes
executor adapter against an actual cluster -- submit through the control
plane, watch a real pod run, land the result in lookout -- the analog of
ref:e2e/armadactl_test/armadactl_test.go:20-80 against a kind cluster
(ref:e2e/setup/kind.yaml).

Skipped unless ARMADA_KIND_E2E=1 AND a reachable kubeconfig'd cluster
exists:

    kind create cluster
    ARMADA_KIND_E2E=1 python -m pytest tests/test_kind_e2e.py -v

The kubeconfig loader itself (mTLS client certs, inline data, contexts) is
unit-tested below without a cluster."""

import base64
import os
import time

import pytest

from armada_tpu.executor.kubeconfig import load_kubeconfig

pytestmark = []


def _cluster_available() -> tuple[bool, str]:
    if os.environ.get("ARMADA_KIND_E2E") != "1":
        return False, "set ARMADA_KIND_E2E=1 (and have a kind cluster) to run"
    try:
        kw = load_kubeconfig()
    except (OSError, ValueError) as e:
        return False, f"no kubeconfig: {e}"
    import ssl
    import urllib.request

    try:
        ctx = ssl.create_default_context(cafile=kw.get("ca_file"))
        if kw.get("client_cert_file"):
            ctx.load_cert_chain(kw["client_cert_file"], kw.get("client_key_file"))
        req = urllib.request.Request(kw["base_url"] + "/version")
        if kw.get("token"):
            req.add_header("Authorization", f"Bearer {kw['token']}")
        with urllib.request.urlopen(req, timeout=5, context=ctx):
            pass
    except Exception as e:  # noqa: BLE001 - any transport failure = skip
        return False, f"cluster unreachable: {e}"
    return True, ""


_OK, _REASON = _cluster_available()


@pytest.mark.skipif(not _OK, reason=_REASON or "kind cluster not available")
def test_submit_to_succeeded_on_real_cluster(tmp_path):
    """submit -> schedule -> real pod -> Succeeded -> lookout row."""
    from armada_tpu.executor import ExecutorService
    from armada_tpu.executor.kubernetes import KubernetesClusterContext
    from armada_tpu.ingest.pipeline import IngestionPipeline
    from armada_tpu.lookout import LookoutDb, LookoutQueries, lookout_converter
    from armada_tpu.server import JobSubmitItem, QueueRecord
    from tests.control_plane import ControlPlane

    plane = ControlPlane.build(tmp_path, executor_specs={})
    factory = plane.config.resource_list_factory()
    kw = load_kubeconfig()
    ctx = KubernetesClusterContext(
        kw.pop("base_url"),
        factory,
        executor_id="kind-e2e",
        default_image="busybox:stable",
        **kw,
    )
    ex = ExecutorService("kind-e2e", "default", ctx, plane.executor_api, factory)
    lookoutdb = LookoutDb(":memory:")
    pipeline = IngestionPipeline(
        plane.log, lookoutdb, lookout_converter, consumer_name="lookout"
    )
    queries = LookoutQueries(lookoutdb)
    try:
        plane.server.create_queue(QueueRecord("kind-q"))
        (jid,) = plane.server.submit_jobs(
            "kind-q",
            "kind-js",
            [
                JobSubmitItem(
                    resources={"cpu": "100m", "memory": "64Mi"},
                    # COMMAND_ANNOTATION takes a JSON list (kubernetes.py)
                    annotations={"armada-tpu.io/command": '["true"]'},
                )
            ],
        )
        deadline = time.time() + 180
        state = None
        while time.time() < deadline:
            ex.run_once()
            plane.ingest()
            plane.scheduler.cycle()
            ex.report_cycle()
            ex.cleanup()
            plane.ingest()
            plane.scheduler.cycle()
            pipeline.run_until_caught_up()
            details = queries.get_job_details(jid)
            state = details and details["state"]
            if state in ("SUCCEEDED", "FAILED"):
                break
            time.sleep(2)
        assert state == "SUCCEEDED", f"job ended {state!r}"
        details = queries.get_job_details(jid)
        assert details["runs"] and details["runs"][0]["node"]
    finally:
        # leave no pods behind on the shared cluster
        try:
            for run_id in list(ctx._pods):
                ctx.delete_pod(run_id)
        except Exception:
            pass
        lookoutdb.close()
        plane.close()


# --- kubeconfig loader unit tests (no cluster needed) -----------------------


def test_load_kubeconfig_client_certs_and_inline_data(tmp_path):
    ca = base64.b64encode(b"CA PEM").decode()
    cert = base64.b64encode(b"CERT PEM").decode()
    key = base64.b64encode(b"KEY PEM").decode()
    cfg = tmp_path / "kubeconfig"
    cfg.write_text(
        f"""
apiVersion: v1
kind: Config
current-context: kind-kind
contexts:
  - name: kind-kind
    context: {{cluster: kind, user: kind-user}}
clusters:
  - name: kind
    cluster:
      server: https://127.0.0.1:6443
      certificate-authority-data: {ca}
users:
  - name: kind-user
    user:
      client-certificate-data: {cert}
      client-key-data: {key}
"""
    )
    kw = load_kubeconfig(cfg.as_posix())
    assert kw["base_url"] == "https://127.0.0.1:6443"
    assert open(kw["ca_file"], "rb").read() == b"CA PEM"
    assert open(kw["client_cert_file"], "rb").read() == b"CERT PEM"
    assert open(kw["client_key_file"], "rb").read() == b"KEY PEM"
    assert "token" not in kw


def test_load_kubeconfig_token_user_and_explicit_context(tmp_path):
    cfg = tmp_path / "kubeconfig"
    cfg.write_text(
        """
apiVersion: v1
current-context: other
contexts:
  - name: other
    context: {cluster: c2, user: u2}
  - name: tokeny
    context: {cluster: c1, user: u1}
clusters:
  - name: c1
    cluster: {server: "https://10.0.0.1:6443", insecure-skip-tls-verify: true}
  - name: c2
    cluster: {server: "https://10.0.0.2:6443"}
users:
  - name: u1
    user: {token: sekrit}
  - name: u2
    user: {}
"""
    )
    kw = load_kubeconfig(cfg.as_posix(), context="tokeny")
    assert kw["base_url"] == "https://10.0.0.1:6443"
    assert kw["token"] == "sekrit"
    assert kw["insecure"] is True
    with pytest.raises(ValueError):
        load_kubeconfig(cfg.as_posix(), context="missing")
