"""armada-lint CI entrypoint: the whole tree must pass.

Runs every registered rule (armada_tpu/analysis/lint.py; docs/lint.md is
the catalogue) over all authored Python in the repo.  Exit 0 = clean;
exit 1 = unsuppressed violations, printed one per line as
``path:line:col: [rule] message``.

    python tools/lint.py                # human output
    python tools/lint.py --json         # ONE JSON line (bench/ops tooling)
    python tools/lint.py --list-rules   # rule names + one-line summaries
    python tools/lint.py path.py ...    # restrict to specific files
    python tools/lint.py --diff         # only files changed vs HEAD
    python tools/lint.py --diff main    # ... vs an arbitrary git ref
    python tools/lint.py --stats        # suppression census (rule -> allows)
    python tools/lint.py --jobs 4       # parallel per-file analysis
    python tools/lint.py --cache        # reuse .lint-cache.json entries

``--diff [REF]`` is the pre-commit scope: files changed vs
merge-base(REF, HEAD) plus untracked, with renames followed to their NEW
path and deletions skipped (``git diff --name-status -M``).  ``--cache``
keeps a content-hash-keyed summary cache at ``.lint-cache.json`` (git-
ignored): an entry replays its recorded findings only while the linted
file AND every project module its dataflow analysis consulted keep their
recorded hashes, and the whole cache is dropped when the engine itself
(lint.py/dataflow.py) changes.  Combine ``--cache --jobs N`` for the
fastest warm full-tree walk.

The fast test tier runs this via tests/test_lint.py (the self-hosting
gate), so a new violation fails CI the same cycle it lands.
"""

from __future__ import annotations

import argparse
import functools
import hashlib
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from armada_tpu.analysis import dataflow as _df  # noqa: E402
from armada_tpu.analysis import lint  # noqa: E402

CACHE_NAME = ".lint-cache.json"
_ENGINE_FILES = (
    "armada_tpu/analysis/lint.py",
    "armada_tpu/analysis/dataflow.py",
)


def _walk_paths(root: str) -> list[str]:
    return list(lint.iter_python_files(root))


def _diff_paths(root: str, ref: str) -> list[str]:
    """Authored .py files changed vs `ref` (plus untracked), filtered by
    the same exclusions as the full walk -- the cheap pre-commit scope.
    Diffs against merge-base(ref, HEAD), not ref itself: on a branch
    behind `ref`, two-dot `git diff ref` would also surface every file
    ref changed that the branch never touched."""
    mb = subprocess.run(
        ["git", "merge-base", ref, "HEAD"],
        capture_output=True,
        text=True,
        cwd=root,
    )
    if mb.returncode != 0:
        raise SystemExit(
            f"armada-lint: --diff {ref}: {mb.stderr.strip() or 'git merge-base failed'}"
        )
    base = mb.stdout.strip()
    # --name-status -M: a rename surfaces as `R<score>\told\tnew` -- lint
    # the NEW path (name-only would list the old one, which may be gone);
    # a deletion is `D\tpath` -- nothing on disk to lint, skip it rather
    # than crash on the read.
    status_rows = subprocess.run(
        ["git", "diff", "--name-status", "-M", base, "--", "*.py"],
        capture_output=True,
        text=True,
        cwd=root,
        check=True,
    ).stdout.splitlines()
    changed = []
    for row in status_rows:
        parts = row.rstrip("\n").split("\t")
        if len(parts) < 2 or not parts[0]:
            continue
        if parts[0].startswith("D"):
            continue
        changed.append(parts[-1])
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard", "--", "*.py"],
        capture_output=True,
        text=True,
        cwd=root,
        check=True,
    ).stdout.splitlines()
    # Reuse the walk's exclusion decisions exactly: intersect with it.
    walk = {
        os.path.relpath(p, root).replace(os.sep, "/") for p in _walk_paths(root)
    }
    out = []
    for rel in sorted(set(changed) | set(untracked)):
        rel = rel.strip().replace(os.sep, "/")
        if rel in walk and os.path.exists(os.path.join(root, rel)):
            out.append(os.path.join(root, rel))
    return out


def _lint_paths(paths: list[str], root: str, jobs: int) -> list:
    if jobs > 1 and len(paths) > 1:
        import multiprocessing

        worker = functools.partial(lint.lint_file, root=root)
        with multiprocessing.Pool(jobs) as pool:
            per_file = pool.map(worker, paths, chunksize=8)
        findings = [f for fs in per_file for f in fs]
    else:
        findings = []
        for p in paths:
            findings.extend(lint.lint_file(p, root))
    return findings


def _engine_hash(root: str) -> str:
    """One key for the analysis engine itself: any lint.py/dataflow.py
    edit invalidates the WHOLE cache (rules and lattice both change what
    a file's findings mean, independent of the file's own content)."""
    h = hashlib.sha256()
    for rel in _ENGINE_FILES:
        with open(os.path.join(root, rel), "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()


def _load_cache(root: str, engine: str) -> dict:
    try:
        with open(os.path.join(root, CACHE_NAME), "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict) or doc.get("engine") != engine:
        return {}
    files = doc.get("files")
    return files if isinstance(files, dict) else {}


def _lint_paths_cached(paths: list[str], root: str, jobs: int) -> list:
    """Cache-aware walk: serve findings for files whose recorded hash map
    (the file + every dataflow dep, transitively) still matches; lint
    only the misses (through the deps-returning worker so their entries
    can be recorded); rewrite the cache."""
    engine = _engine_hash(root)
    cached = _load_cache(root, engine)
    cur: dict = {}

    def cur_hash(rel: str):
        if rel not in cur:
            try:
                cur[rel] = _df.content_hash(os.path.join(root, rel))
            except OSError:
                cur[rel] = None  # a recorded dep vanished: stale
        return cur[rel]

    findings: list = []
    fresh: dict = {}
    misses: list[str] = []
    for p in paths:
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        ent = cached.get(rel)
        deps = ent.get("deps") if isinstance(ent, dict) else None
        if deps and all(cur_hash(d) == h for d, h in deps.items()):
            findings.extend(lint.Finding(**d) for d in ent.get("findings", []))
            fresh[rel] = ent
            continue
        misses.append(p)

    if misses:
        worker = functools.partial(lint.lint_file_deps, root=root)
        if jobs > 1 and len(misses) > 1:
            import multiprocessing

            with multiprocessing.Pool(jobs) as pool:
                results = pool.map(worker, misses, chunksize=8)
        else:
            results = [worker(p) for p in misses]
        for p, (fs, deps) in zip(misses, results):
            rel = os.path.relpath(p, root).replace(os.sep, "/")
            findings.extend(fs)
            fresh[rel] = {"deps": deps, "findings": [f.as_dict() for f in fs]}

    # Entries for files outside this run (e.g. a --diff scope) survive
    # untouched; their own hash maps keep them honest next time.
    for rel, ent in cached.items():
        fresh.setdefault(rel, ent)
    try:
        with open(os.path.join(root, CACHE_NAME), "w", encoding="utf-8") as fh:
            json.dump({"engine": engine, "files": fresh}, fh)
    except OSError:
        pass  # a read-only checkout still lints, just never warms
    return findings


def _print_stats(root: str) -> None:
    """The suppression census: rule -> count -> reasons, so stale allows
    are visible (remove the site, the row disappears)."""
    rows = lint.suppression_census(root)
    by_rule: dict[str, list] = {}
    for rel, line, rule_name, reason in rows:
        by_rule.setdefault(rule_name, []).append((rel, line, reason))
    print(f"armada-lint: {len(rows)} reasoned allow(s), {len(by_rule)} rule(s)")
    for rule_name in sorted(by_rule, key=lambda r: (-len(by_rule[r]), r)):
        sites = by_rule[rule_name]
        print(f"\n{rule_name}: {len(sites)} allow(s)")
        for rel, line, reason in sites:
            print(f"  {rel}:{line}: {reason}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files to lint (default: repo)")
    ap.add_argument(
        "--json",
        action="store_true",
        help="one JSON line: {ok, files, violations, findings[]}",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    ap.add_argument(
        "--diff",
        nargs="?",
        const="HEAD",
        default=None,
        metavar="REF",
        help="lint only files changed vs a git ref (default HEAD) "
        "plus untracked files -- the cheap pre-commit scope",
    )
    ap.add_argument(
        "--stats",
        action="store_true",
        help="print the suppression census (rule -> count -> reasons)",
    )
    ap.add_argument(
        "--jobs",
        type=int,
        default=1,
        metavar="N",
        help="parallel per-file analysis processes (default 1)",
    )
    ap.add_argument(
        "--cache",
        action="store_true",
        help="reuse .lint-cache.json entries whose file+dep content "
        "hashes are unchanged (engine edits drop the whole cache)",
    )
    args = ap.parse_args(argv)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    if args.list_rules:
        for r in lint.RULES:
            print(f"{r.name}: {r.summary}")
        return 0

    if args.stats:
        _print_stats(root)
        return 0

    if args.paths:
        paths = [os.path.abspath(p) for p in args.paths]
    elif args.diff is not None:
        paths = _diff_paths(root, args.diff)
    else:
        paths = _walk_paths(root)
    n = len(paths)
    if args.cache:
        findings = _lint_paths_cached(paths, root, args.jobs)
    else:
        findings = _lint_paths(paths, root, args.jobs)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    if args.json:
        print(
            json.dumps(
                {
                    "tool": "armada_lint",
                    "ok": not findings,
                    "files": n,
                    "rules": len(lint.RULES),
                    "violations": len(findings),
                    "findings": [f.as_dict() for f in findings],
                }
            )
        )
    else:
        for f in findings:
            print(f.format())
        print(
            f"armada-lint: {n} files, {len(lint.RULES)} rules, "
            f"{len(findings)} violation(s)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `lint.py --stats | head` closes the pipe early; that is the
        # reader's prerogative, not an error.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
