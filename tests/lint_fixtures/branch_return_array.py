# Fixture for rule `branch-return-array` (linted under armada_tpu/models/).
# The twin call is syntactically IDENTICAL to the TP; the branches behind
# it return a freshly computed ROW (the sanctioned rows-out idiom), not the
# whole buffer -- only return-value provenance separates the two calls.
import jax


def commit(alloc, row, node, hit):
    def on_hit(a):
        return a.at[node].add(row)

    def on_miss(a):
        return a

    alloc = jax.lax.cond(hit, on_hit, on_miss, alloc)  # TP

    def hit_row(a):
        return a[node] + row

    def miss_row(a):
        return a[node]

    new_row = jax.lax.cond(hit, hit_row, miss_row, alloc)  # twin
    # rows out: the write-back happens OUTSIDE the switch, once
    alloc = alloc.at[node].set(new_row)
    return alloc
