"""Queue repository: queue configuration behind the Submit API.

Equivalent of the reference's `internal/server/queue/queue_repository.go`
(PostgresQueueRepository:47) on the control-plane SQLite DB; the scheduler's
queue provider (the reference's QueueCache, internal/scheduler/queue/
queue_cache.go:27) reads the same table.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from armada_tpu.core.types import Queue
from armada_tpu.ingest.schedulerdb import SchedulerDb


@dataclasses.dataclass(frozen=True)
class QueueRecord:
    """A queue as configured by operators (pkg/api Queue)."""

    name: str
    # priority_factor in the reference; weight = 1/priority_factor there.
    weight: float = 1.0
    cordoned: bool = False
    owners: tuple[str, ...] = ()
    groups: tuple[str, ...] = ()
    labels: dict = dataclasses.field(default_factory=dict)

    def to_queue(self) -> Queue:
        return Queue(self.name, self.weight)


class QueueNotFound(KeyError):
    pass


class QueueAlreadyExists(ValueError):
    pass


class QueueRepository:
    def __init__(self, db: SchedulerDb, publisher=None, clock=None):
        """publisher: when set, queue CRUD is ALSO event-sourced onto the
        "$control-plane" stream (QueueUpsert/QueueDelete) so replicas that
        tail the log converge on the same queues table (cross-host HA; the
        reference keeps queues in shared Postgres instead).  The direct DB
        write stays for read-your-writes -- the ingester's re-apply of the
        same event is an idempotent upsert."""
        self._db = db
        self._publisher = publisher
        self._clock = clock or __import__("time").time

    def _publish(self, event) -> None:
        if self._publisher is None:
            return
        from armada_tpu.events import events_pb2 as pb
        from armada_tpu.server.controlplane import CONTROL_PLANE_JOBSET

        event.created_ns = int(self._clock() * 1e9)
        self._publisher.publish(
            [
                pb.EventSequence(
                    queue="",
                    jobset=CONTROL_PLANE_JOBSET,
                    events=[event],
                )
            ]
        )

    def create(self, record: QueueRecord) -> None:
        if self._db.get_queue(record.name) is not None:
            raise QueueAlreadyExists(record.name)
        self._upsert(record)

    def update(self, record: QueueRecord) -> None:
        if self._db.get_queue(record.name) is None:
            raise QueueNotFound(record.name)
        self._upsert(record)

    def _upsert(self, record: QueueRecord) -> None:
        if record.weight <= 0:
            raise ValueError(f"queue {record.name}: weight must be > 0")
        if not record.name:
            raise ValueError("queue name must be non-empty")
        self._db.upsert_queue(
            record.name,
            weight=record.weight,
            cordoned=record.cordoned,
            owners=list(record.owners),
            groups=list(record.groups),
            labels=record.labels,
        )
        from armada_tpu.events import events_pb2 as pb

        self._publish(
            pb.Event(
                queue_upsert=pb.QueueUpsert(
                    name=record.name,
                    weight=record.weight,
                    cordoned=record.cordoned,
                    owners=list(record.owners),
                    groups=list(record.groups),
                    labels={k: str(v) for k, v in record.labels.items()},
                )
            )
        )

    def delete(self, name: str) -> None:
        self._db.delete_queue(name)
        from armada_tpu.events import events_pb2 as pb

        self._publish(pb.Event(queue_delete=pb.QueueDelete(name=name)))

    def get(self, name: str) -> Optional[QueueRecord]:
        row = self._db.get_queue(name)
        return _from_row(row) if row is not None else None

    def list(self) -> list[QueueRecord]:
        return [_from_row(r) for r in self._db.list_queues()]

    def scheduling_queues(self) -> list[Queue]:
        """Queues as the scheduling algorithm sees them: uncordoned, weighted
        (the scheduler's queue provider; cordoned queues keep their jobs but
        receive nothing new)."""
        return [q.to_queue() for q in self.list() if not q.cordoned]


def _from_row(row) -> QueueRecord:
    return QueueRecord(
        name=row["name"],
        weight=float(row["weight"]),
        cordoned=bool(row["cordoned"]),
        owners=tuple(json.loads(row["owners"])),
        groups=tuple(json.loads(row["groups_json"])),
        labels=json.loads(row["labels_json"]),
    )
