"""Durable partitioned event log (native C++ store) + publisher/consumer.

The framework's Pulsar equivalent: the ordered, replayable source of truth
(SURVEY.md section 2.5; reference internal/common/pulsarutils,
internal/scheduler/publisher.go).
"""

from armada_tpu.eventlog.log import EventLog, Message
from armada_tpu.eventlog.publisher import (
    ConsumedBatch,
    Consumer,
    PublishedRef,
    Publisher,
    jobset_key,
    partition_for_key,
    wait_for_markers,
)

__all__ = [
    "EventLog",
    "Message",
    "Publisher",
    "Consumer",
    "ConsumedBatch",
    "PublishedRef",
    "jobset_key",
    "partition_for_key",
    "wait_for_markers",
]
