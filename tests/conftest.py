"""Test harness: force an 8-device virtual CPU mesh before jax is imported.

Sharding/collective paths are validated on virtual CPU devices, mirroring how the
driver dry-runs the multi-chip path (xla_force_host_platform_device_count); real-TPU
execution is covered by bench.py on hardware.
"""

import os

# Force CPU even though the session presets JAX_PLATFORMS=axon (the real TPU):
# unit tests validate logic + sharding on the virtual 8-device mesh; bench.py is
# what runs on hardware.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon plugin's registration force-sets jax_platforms="axon,cpu", overriding
# the env var, which would make even CPU tests initialize the remote TPU tunnel
# (and block whenever the chip is busy or the tunnel is down).  Re-pin to cpu at
# the config level after import, before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

_last_module = [None]


@pytest.fixture(autouse=True)
def _tsan_violations_fail_tests():
    """ARMADA_TSAN=1 (analysis/tsan): any lock-order inversion or
    generation-stale write recorded during a test FAILS it -- the race
    harness turns zombie-worker races into red tests instead of debugging
    sessions.  Zero-cost no-op when the harness is disarmed."""
    from armada_tpu.analysis import tsan

    if not tsan.enabled():
        yield
        return
    tsan.reset()
    yield
    found = tsan.take_violations()
    assert not found, "tsan violations:\n" + "\n".join(found)


@pytest.fixture(autouse=True)
def _bound_xla_mappings(request):
    """Drop compiled executables at each module boundary.

    Every round-kernel compile holds ~660 VIRTUAL MEMORY MAPPINGS (XLA:CPU
    code + buffer segments); vm.max_map_count is 65530, so ~100 live
    executables make the next mmap fail -- surfacing as MemoryError with
    gigabytes of RAM free (this killed the full suite at a deterministic
    test twice in round 3).  Clearing per MODULE bounds live mappings while
    keeping within-module recompiles at zero."""
    module = request.node.nodeid.split("::", 1)[0]
    if _last_module[0] is not None and module != _last_module[0]:
        jax.clear_caches()
    _last_module[0] = module
    yield


# --- test tiers --------------------------------------------------------------
# `-m fast` = the <10-minute tier (driver/CI smoke; CLAUDE.md contract):
# wholly-fast modules run in full, every OTHER module contributes its first
# few tests so no component goes unrepresented.  The full gauntlet (no -m)
# is unchanged.  Modules NOT listed here default to the representative rule,
# so a new test module is automatically covered by the fast tier.

# Modules cheap enough to run whole (unit-ish: no kernel compiles at large
# shapes, no multi-second worlds).
_FAST_MODULES = {
    "tests/test_core_keys.py",
    "tests/test_core_resources.py",
    "tests/test_ops_fairness.py",
    "tests/test_ops_fit_packing.py",
    "tests/test_jobdb.py",
    "tests/test_eventlog.py",
    "tests/test_ingest.py",
    "tests/test_server.py",
    "tests/test_authn.py",
    "tests/test_health.py",
    "tests/test_logging_context.py",
    "tests/test_ratelimit.py",
    "tests/test_quarantine.py",
    "tests/test_serve_config.py",
    "tests/test_cli.py",
    "tests/test_short_job_penalty.py",
    "tests/test_submitcheck.py",
    "tests/test_kube_leader.py",
    "tests/test_reports_proxy.py",
    "tests/test_podchecks.py",
    "tests/test_binoculars.py",
    "tests/test_airflow_operator.py",
    "tests/test_metric_events.py",
    "tests/test_submit_brake.py",
    "tests/test_lookout.py",
    # armada-lint self-hosting gate: the fast tier IS the CI path that
    # keeps the tree lint-clean (tools/lint.py; docs/lint.md).  The
    # dataflow engine behind the v2 semantic rules is pinned separately
    # so rule bugs and lattice bugs fail different tests.
    "tests/test_lint.py",
    "tests/test_dataflow.py",
    # soak-subsystem units: histogram-vs-numpy-oracle exactness + the
    # loadgen arrival/mix/lifecycle machinery (no kernel compiles).
    "tests/test_slo_metrics.py",
    "tests/test_loadgen.py",
    # mesh serving plane: kernel compiles, but all at tiny bucket-64 shapes
    # on the 8-device virtual mesh (~30s whole); the fast tier must carry
    # BOTH the churn equality (burst incl.) and the degrade-ladder drill.
    "tests/test_mesh_serving.py",
}
# How many representative tests each remaining module contributes.
_FAST_PICKS = 2
# Kernel-compiling integration modules contribute ONE representative (each
# pick costs a 10-40s XLA:CPU compile on the 1-CPU round host; picks=2
# measured 13:38 for the tier, over the <10-min contract).
_FAST_PICKS_OVERRIDE = {
    "tests/test_market_columnar.py": 1,
    "tests/test_parity_full.py": 1,
    "tests/test_parity.py": 1,
    "tests/test_scheduler_service.py": 1,
    "tests/test_e2e_stack.py": 1,
    "tests/test_golden_traces.py": 1,
    "tests/test_incremental.py": 1,
    "tests/test_home_away.py": 1,
    "tests/test_floating_market.py": 1,
    "tests/test_gang_uniformity.py": 1,
    "tests/test_round_scheduler.py": 1,
    "tests/test_market_pricing.py": 1,
    "tests/test_sidecar.py": 1,
    "tests/test_simulator.py": 1,
    "tests/test_optimiser.py": 1,
    "tests/test_executor_loop.py": 1,
    "tests/test_anti_affinity.py": 1,
    "tests/test_gang_rollback.py": 1,
    "tests/test_round_termination.py": 1,
    "tests/test_decode_compact.py": 1,
    "tests/test_slab_delta.py": 1,
    "tests/test_parallel_sharding.py": 1,
    # 2 representatives + the explicitly-marked ARMADA_PIPELINE=0 parity
    # guard (the sequential escape hatch must not rot out of the fast tier).
    "tests/test_pipeline.py": 2,
    # first 4 = the cheap in-process race-harness drills (the subprocess
    # pipeline/faults-under-ARMADA_TSAN=1 leg stays full-tier only).
    "tests/test_tsan.py": 4,
    # first test = the chaos-under-load smoke (mid-soak device hang: no
    # SLO gap, no tsan violations, nothing dropped/double-leased) -- the
    # soak subsystem's acceptance gate; the clean window + subprocess
    # JSON-contract legs stay full-tier.
    "tests/test_soak.py": 1,
}
# Never in the fast tier (opt-in external deps / native builds).
_FAST_EXCLUDE_MODULES = {
    "tests/test_kind_e2e.py",
    "tests/test_cpp_client.py",
    "tests/test_client_codegen.py",
}


def pytest_collection_modifyitems(config, items):
    seen: dict = {}
    for item in items:
        mod = item.location[0]
        if mod in _FAST_EXCLUDE_MODULES:
            continue
        if mod in _FAST_MODULES:
            item.add_marker(pytest.mark.fast)
            continue
        n = seen.get(mod, 0)
        if n < _FAST_PICKS_OVERRIDE.get(mod, _FAST_PICKS):
            item.add_marker(pytest.mark.fast)
            seen[mod] = n + 1
