"""EventSequence -> DbOperation conversion for the scheduler database.

Equivalent of the reference's scheduleringester InstructionConverter
(internal/scheduleringester/instructions.go:57-140): each event type maps to
one typed bulk op; the batch is then compacted via merge/reorder
(dbops.merge_ops) before hitting SQLite.
"""

from __future__ import annotations

from typing import Iterable

from armada_tpu.events import events_pb2 as pb
from armada_tpu.ingest import dbops as ops


def convert_sequences(
    sequences: Iterable[pb.EventSequence],
) -> list[ops.DbOperation]:
    raw: list[ops.DbOperation] = []
    for seq in sequences:
        for ev in seq.events:
            result = _convert_event(seq, ev)
            if result is None:
                continue
            if isinstance(result, list):
                raw.extend(result)
            else:
                raw.append(result)
    return ops.merge_ops(raw)


def _convert_event(seq: pb.EventSequence, ev: pb.Event):
    """Returns one DbOperation, a list of them, or None (event irrelevant to
    the scheduler DB)."""
    kind = ev.WhichOneof("event")
    if kind == "submit_job":
        e = ev.submit_job
        return ops.InsertJobs(
            jobs={
                e.job_id: {
                    "job_id": e.job_id,
                    "queue": seq.queue,
                    "jobset": seq.jobset,
                    "priority": int(e.spec.priority),
                    "submitted_ns": int(ev.created_ns),
                    # deterministic: map-field entry order is otherwise
                    # process-dependent, and the partition-parallel plane
                    # converts in WORKER processes -- the stored blob must
                    # be byte-identical to the serial pipeline's
                    # (test_ingest_shards pins materialized bit-equality)
                    "spec": e.spec.SerializeToString(deterministic=True),
                }
            }
        )
    if kind == "job_validated":
        e = ev.job_validated
        return ops.MarkJobsValidated(pools_by_job={e.job_id: tuple(e.pools)})
    if kind == "reprioritise_job":
        e = ev.reprioritise_job
        return ops.UpdateJobPriorities(priority_by_job={e.job_id: int(e.priority)})
    if kind == "reprioritised_job":
        e = ev.reprioritised_job
        return ops.UpdateJobPriorities(priority_by_job={e.job_id: int(e.priority)})
    if kind == "cancel_job":
        return ops.MarkJobsCancelRequested(job_ids={ev.cancel_job.job_id})
    if kind == "cancel_job_set":
        e = ev.cancel_job_set
        states = set(e.states)
        return ops.MarkJobSetCancelRequested(
            queue=seq.queue,
            jobset=seq.jobset,
            cancel_queued=not states or "queued" in states,
            cancel_leased=not states or "leased" in states,
        )
    if kind == "cancelled_job":
        return ops.MarkJobsCancelled(job_ids={ev.cancelled_job.job_id})
    if kind == "job_succeeded":
        return ops.MarkJobsSucceeded(job_ids={ev.job_succeeded.job_id})
    if kind == "job_errors":
        e = ev.job_errors
        if any(err.terminal for err in e.errors):
            return ops.MarkJobsFailed(job_ids={e.job_id})
        return None
    if kind == "job_requeued":
        e = ev.job_requeued
        return ops.UpdateJobQueuedState(
            state_by_job={e.job_id: (True, int(e.update_sequence_number))}
        )
    if kind == "preempt_job":
        return ops.MarkJobsPreemptRequested(job_ids={ev.preempt_job.job_id})
    # control-plane events (the "$control-plane" stream; reference
    # scheduleringester ControlPlaneEventsInstructionConverter)
    if kind == "queue_upsert":
        e = ev.queue_upsert
        return ops.UpsertQueues(
            queues_by_name={
                e.name: {
                    "weight": float(e.weight),
                    "cordoned": bool(e.cordoned),
                    "owners": list(e.owners),
                    "groups": list(e.groups),
                    "labels": dict(e.labels),
                }
            }
        )
    if kind == "queue_delete":
        return ops.DeleteQueues(names={ev.queue_delete.name})
    if kind == "executor_settings_upsert":
        e = ev.executor_settings_upsert
        return ops.UpsertExecutorSettings(
            settings_by_name={
                e.name: {
                    "cordoned": bool(e.cordoned),
                    "cordon_reason": e.cordon_reason,
                    "set_by_user": e.set_by_user,
                }
            }
        )
    if kind == "executor_settings_delete":
        return ops.DeleteExecutorSettings(
            names={ev.executor_settings_delete.name}
        )
    if kind == "preempt_on_executor":
        e = ev.preempt_on_executor
        return ops.PreemptOnExecutor(
            executor=e.name,
            queues=tuple(e.queues),
            priority_classes=tuple(e.priority_classes),
        )
    if kind == "cancel_on_executor":
        e = ev.cancel_on_executor
        return ops.CancelOnExecutor(
            executor=e.name,
            queues=tuple(e.queues),
            priority_classes=tuple(e.priority_classes),
        )
    if kind == "preempt_on_queue":
        e = ev.preempt_on_queue
        return ops.PreemptOnQueue(
            queue=e.name, priority_classes=tuple(e.priority_classes)
        )
    if kind == "cancel_on_queue":
        e = ev.cancel_on_queue
        return ops.CancelOnQueue(
            queue=e.name,
            priority_classes=tuple(e.priority_classes),
            job_states=tuple(e.job_states),
        )
    if kind == "reprioritise_job_set":
        return ops.UpdateJobSetPriority(
            queue=seq.queue,
            jobset=seq.jobset,
            priority=int(ev.reprioritise_job_set.priority),
        )
    if kind == "job_run_leased":
        e = ev.job_run_leased
        return [
            ops.InsertRuns(
                runs={
                    e.run_id: {
                        "run_id": e.run_id,
                        "job_id": e.job_id,
                        "created_ns": int(ev.created_ns),
                        "executor": e.executor_id,
                        "node_id": e.node_id,
                        "pool": e.pool,
                        "scheduled_at_priority": int(e.scheduled_at_priority),
                        "pool_scheduled_away": int(e.pool_scheduled_away),
                    }
                }
            ),
            # The lease flips the job to not-queued at the event's sequence
            # number (reference: instructions.go:225-228).
            ops.UpdateJobQueuedState(
                state_by_job={
                    e.job_id: (False, int(e.update_sequence_number))
                }
            ),
        ]
    if kind == "job_run_assigned":
        e = ev.job_run_assigned
        return ops.MarkRunsPending(runs={e.run_id: e.job_id})
    if kind == "job_run_running":
        e = ev.job_run_running
        return ops.MarkRunsRunning(
            runs={e.run_id: e.job_id}, times={e.run_id: int(ev.created_ns)}
        )
    if kind == "job_run_succeeded":
        e = ev.job_run_succeeded
        return ops.MarkRunsSucceeded(runs={e.run_id: e.job_id})
    if kind == "job_run_errors":
        e = ev.job_run_errors
        out = ops.InsertJobRunErrors(
            errors={
                e.run_id: [
                    (err.reason, err.message, err.terminal) for err in e.errors
                ]
            },
            job_by_run={e.run_id: e.job_id},
        )
        if any(err.terminal for err in e.errors):
            # A terminal run error also fails the run (instructions.go
            # handleJobRunErrors).
            return [out, ops.MarkRunsFailed(runs={e.run_id: e.job_id})]
        if any(err.lease_returned for err in e.errors):
            # Lease returned: run over, job may retry (MarkRunsReturned).
            return [out, ops.MarkRunsReturned(runs={e.run_id: e.job_id})]
        return out
    if kind == "job_run_preempted":
        e = ev.job_run_preempted
        return ops.MarkRunsPreempted(runs={e.run_id: e.job_id})
    if kind == "job_run_preemption_requested":
        e = ev.job_run_preemption_requested
        return ops.MarkRunsPreemptRequested(runs={e.run_id: e.job_id})
    if kind == "job_run_cancelled":
        e = ev.job_run_cancelled
        return ops.MarkRunsFailed(runs={e.run_id: e.job_id})
    if kind == "partition_marker":
        e = ev.partition_marker
        return ops.InsertPartitionMarker(
            group_id=e.group_id, partition=int(e.partition),
            created_ns=int(ev.created_ns),
        )
    return None
