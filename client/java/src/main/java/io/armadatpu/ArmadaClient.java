/*
 * Thin Java client for the armada-tpu control plane.
 *
 * Mirrors the Python client's approach (armada_tpu/rpc/client.py): generic
 * gRPC method descriptors over the generated protobuf messages -- no
 * grpc-java service codegen needed, only `tools/genclients.sh OUT java`
 * for the message classes (armada_tpu.api.Rpc / armada_tpu.events.Events).
 *
 * Reference parity: client/java (pkg/api bindings); the verbs cover the
 * Submit/Event service surface armadactl exposes plus the Lookout and
 * scheduling-Reports query services (JSON-over-gRPC).
 */
package io.armadatpu;

import armada_tpu.api.Rpc;
import io.grpc.CallOptions;
import io.grpc.ManagedChannel;
import io.grpc.ManagedChannelBuilder;
import io.grpc.Metadata;
import io.grpc.MethodDescriptor;
import io.grpc.protobuf.ProtoUtils;
import io.grpc.stub.ClientCalls;
import io.grpc.stub.MetadataUtils;

import java.util.Iterator;
import java.util.List;

public final class ArmadaClient implements AutoCloseable {

    private final ManagedChannel channel;
    private final io.grpc.Channel stubChannel;

    /**
     * @param target    host:port of the control plane (plaintext gRPC; put a
     *                  TLS terminator in front for production)
     * @param principal rides the x-armada-principal trusted header (dev auth
     *                  chains); pass a bearer token via {@link #withBearer}
     *                  for OIDC/token-review chains instead
     */
    public ArmadaClient(String target, String principal) {
        this.channel = ManagedChannelBuilder.forTarget(target).usePlaintext().build();
        Metadata md = new Metadata();
        md.put(Metadata.Key.of("x-armada-principal", Metadata.ASCII_STRING_MARSHALLER),
                principal);
        this.stubChannel = io.grpc.ClientInterceptors.intercept(
                channel, MetadataUtils.newAttachHeadersInterceptor(md));
    }

    private ArmadaClient(ManagedChannel channel, io.grpc.Channel stubChannel) {
        this.channel = channel;
        this.stubChannel = stubChannel;
    }

    /** The same client with an Authorization: Bearer header (server authn). */
    public static ArmadaClient withBearer(String target, String token) {
        ManagedChannel ch = ManagedChannelBuilder.forTarget(target).usePlaintext().build();
        Metadata md = new Metadata();
        md.put(Metadata.Key.of("authorization", Metadata.ASCII_STRING_MARSHALLER),
                "Bearer " + token);
        return new ArmadaClient(ch, io.grpc.ClientInterceptors.intercept(
                ch, MetadataUtils.newAttachHeadersInterceptor(md)));
    }

    private static <Req extends com.google.protobuf.Message,
                    Res extends com.google.protobuf.Message>
            MethodDescriptor<Req, Res> unary(String fullName, Req defReq, Res defRes) {
        return MethodDescriptor.<Req, Res>newBuilder()
                .setType(MethodDescriptor.MethodType.UNARY)
                .setFullMethodName(fullName)
                .setRequestMarshaller(ProtoUtils.marshaller(defReq))
                .setResponseMarshaller(ProtoUtils.marshaller(defRes))
                .build();
    }

    private <Req extends com.google.protobuf.Message,
             Res extends com.google.protobuf.Message>
            Res call(String fullName, Req req, Res defRes) {
        @SuppressWarnings("unchecked")
        MethodDescriptor<Req, Res> md =
                unary(fullName, (Req) req.getDefaultInstanceForType(), defRes);
        return ClientCalls.blockingUnaryCall(stubChannel, md, CallOptions.DEFAULT, req);
    }

    // --- submit surface (armada_tpu.api.Submit) ----------------------------

    public List<String> submitJobs(String queue, String jobset,
                                   List<Rpc.SubmitItem> items) {
        Rpc.SubmitJobsRequest req = Rpc.SubmitJobsRequest.newBuilder()
                .setQueue(queue).setJobset(jobset).addAllItems(items).build();
        return call("armada_tpu.api.Submit/SubmitJobs", req,
                Rpc.SubmitJobsResponse.getDefaultInstance()).getJobIdsList();
    }

    public void cancelJobs(String queue, String jobset, List<String> jobIds,
                           String reason) {
        call("armada_tpu.api.Submit/CancelJobs",
                Rpc.CancelJobsRequest.newBuilder().setQueue(queue).setJobset(jobset)
                        .addAllJobIds(jobIds).setReason(reason).build(),
                Rpc.Empty.getDefaultInstance());
    }

    public void preemptJobs(String queue, String jobset, List<String> jobIds,
                            String reason) {
        call("armada_tpu.api.Submit/PreemptJobs",
                Rpc.PreemptJobsRequest.newBuilder().setQueue(queue).setJobset(jobset)
                        .addAllJobIds(jobIds).setReason(reason).build(),
                Rpc.Empty.getDefaultInstance());
    }

    public void reprioritizeJobs(String queue, String jobset, long priority,
                                 List<String> jobIds) {
        call("armada_tpu.api.Submit/ReprioritizeJobs",
                Rpc.ReprioritizeJobsRequest.newBuilder().setQueue(queue)
                        .setJobset(jobset).setPriority(priority)
                        .addAllJobIds(jobIds).build(),
                Rpc.Empty.getDefaultInstance());
    }

    public void createQueue(Rpc.Queue queue) {
        call("armada_tpu.api.Submit/CreateQueue", queue,
                Rpc.Empty.getDefaultInstance());
    }

    public List<Rpc.Queue> listQueues() {
        return call("armada_tpu.api.Submit/ListQueues",
                Rpc.Empty.getDefaultInstance(),
                Rpc.QueueListResponse.getDefaultInstance()).getQueuesList();
    }

    // --- lookout surface (armada_tpu.api.Lookout: JSON-over-gRPC, the
    // reference's REST query shapes) ----------------------------------------

    /** Filtered job page; {@code queryJson} is the lookout query document
     * ({"filters": [...], "order": {...}, "skip": n, "take": n}). */
    public String getJobs(String queryJson) {
        return call("armada_tpu.api.Lookout/GetJobs",
                Rpc.LookoutQuery.newBuilder().setQueryJson(queryJson).build(),
                Rpc.JsonResponse.getDefaultInstance()).getJson();
    }

    /** Grouped counts ({"group_by": "queue"|"jobset"|"state"|"annotation",
     * "filters": [...], "aggregates": [...]}). */
    public String groupJobs(String queryJson) {
        return call("armada_tpu.api.Lookout/GroupJobs",
                Rpc.LookoutQuery.newBuilder().setQueryJson(queryJson).build(),
                Rpc.JsonResponse.getDefaultInstance()).getJson();
    }

    /** Full job details (spec fields, runs, errors, ingress addresses). */
    public String getJobDetails(String jobId) {
        return call("armada_tpu.api.Lookout/GetJobDetails",
                Rpc.QueueGetRequest.newBuilder().setName(jobId).build(),
                Rpc.JsonResponse.getDefaultInstance()).getJson();
    }

    // --- scheduling reports (armada_tpu.api.Reports; followers proxy to
    // the leader, UNAVAILABLE is retryable) ---------------------------------

    public String getJobReport(String jobId) {
        return call("armada_tpu.api.Reports/GetJobReport",
                Rpc.QueueGetRequest.newBuilder().setName(jobId).build(),
                Rpc.JsonResponse.getDefaultInstance()).getJson();
    }

    public String getQueueReport(String queue) {
        return call("armada_tpu.api.Reports/GetQueueReport",
                Rpc.QueueGetRequest.newBuilder().setName(queue).build(),
                Rpc.JsonResponse.getDefaultInstance()).getJson();
    }

    /** Pool scheduling report; "" = every pool. */
    public String getPoolReport(String pool) {
        return call("armada_tpu.api.Reports/GetPoolReport",
                Rpc.QueueGetRequest.newBuilder().setName(pool).build(),
                Rpc.JsonResponse.getDefaultInstance()).getJson();
    }

    // --- event surface (armada_tpu.api.Event) ------------------------------

    /**
     * Stream jobset events from {@code fromIdx}; {@code watch} keeps the
     * stream open for new events ({@code idleTimeoutS} without progress ends
     * it).  Each message's {@code idx} is the resume cursor to persist.
     */
    public Iterator<Rpc.JobSetEventMessage> watch(String queue, String jobset,
                                                  long fromIdx, boolean watch,
                                                  double idleTimeoutS) {
        MethodDescriptor<Rpc.JobSetEventsRequest, Rpc.JobSetEventMessage> md =
                MethodDescriptor.<Rpc.JobSetEventsRequest, Rpc.JobSetEventMessage>newBuilder()
                        .setType(MethodDescriptor.MethodType.SERVER_STREAMING)
                        .setFullMethodName("armada_tpu.api.Event/GetJobSetEvents")
                        .setRequestMarshaller(ProtoUtils.marshaller(
                                Rpc.JobSetEventsRequest.getDefaultInstance()))
                        .setResponseMarshaller(ProtoUtils.marshaller(
                                Rpc.JobSetEventMessage.getDefaultInstance()))
                        .build();
        Rpc.JobSetEventsRequest req = Rpc.JobSetEventsRequest.newBuilder()
                .setQueue(queue).setJobset(jobset).setFromIdx(fromIdx)
                .setWatch(watch).setIdleTimeoutS(idleTimeoutS).build();
        return ClientCalls.blockingServerStreamingCall(
                stubChannel, md, CallOptions.DEFAULT, req);
    }

    @Override
    public void close() {
        channel.shutdown();
    }
}
