"""Native event log + publisher/consumer: durability, ordering, fencing.

Covers the behavior the reference gets from Pulsar (internal/common/pulsarutils;
internal/scheduler/publisher.go:25-60): ordered partitioned append/replay,
chunking, marker fencing, crash recovery of a torn tail.
"""

import os

import pytest

from armada_tpu.eventlog import (
    Consumer,
    EventLog,
    Publisher,
    jobset_key,
    partition_for_key,
    wait_for_markers,
)
from armada_tpu.events import events_pb2 as pb


def submit_seq(queue, jobset, job_ids):
    return pb.EventSequence(
        queue=queue,
        jobset=jobset,
        events=[
            pb.Event(submit_job=pb.SubmitJob(job_id=j, spec=pb.JobSpec()))
            for j in job_ids
        ],
    )


def test_append_read_roundtrip(tmp_path):
    with EventLog(str(tmp_path / "log"), num_partitions=2) as log:
        o1 = log.append(0, b"k1", b"hello")
        o2 = log.append(0, b"k2", b"world")
        assert o2 > o1
        msgs = log.read(0, 0)
        assert [(m.key, m.payload) for m in msgs] == [(b"k1", b"hello"), (b"k2", b"world")]
        assert msgs[0].offset == o1 and msgs[1].offset == o2
        # Reading from the second record's offset skips the first.
        assert [m.payload for m in log.read(0, o2)] == [b"world"]
        assert log.read(1, 0) == []


def test_persistence_across_reopen(tmp_path):
    path = str(tmp_path / "log")
    with EventLog(path, num_partitions=1) as log:
        log.append(0, b"k", b"v1")
        log.append(0, b"k", b"v2")
        log.flush()
        end = log.end_offset(0)
    with EventLog(path, num_partitions=1) as log:
        assert log.end_offset(0) == end
        assert [m.payload for m in log.read(0, 0)] == [b"v1", b"v2"]


def test_torn_tail_truncated_on_reopen(tmp_path):
    path = str(tmp_path / "log")
    with EventLog(path, num_partitions=1) as log:
        log.append(0, b"k", b"complete")
        log.flush()
        good_end = log.end_offset(0)
    # Simulate a crash mid-write: garbage partial record at the tail.
    with open(os.path.join(path, "p0.log"), "ab") as f:
        f.write(b"\xff\xff\xff\x7f partial")
    with EventLog(path, num_partitions=1) as log:
        assert log.end_offset(0) == good_end
        assert [m.payload for m in log.read(0, 0)] == [b"complete"]
        # And appends continue cleanly after recovery.
        log.append(0, b"k", b"after")
        assert [m.payload for m in log.read(0, 0)] == [b"complete", b"after"]


def test_publisher_routes_by_jobset_and_chunks(tmp_path):
    with EventLog(str(tmp_path / "log"), num_partitions=4) as log:
        publisher = Publisher(log, max_events_per_message=10)
        seq = submit_seq("q1", "js1", [f"j{i}" for i in range(25)])
        refs = publisher.publish([seq])
        # 25 events at <=10/message -> 3 chunks, all on the jobset's partition.
        part = partition_for_key(jobset_key("q1", "js1"), 4)
        assert len(refs) == 3
        assert all(r.partition == part for r in refs)
        msgs = log.read(part, 0)
        sizes = [len(pb.EventSequence.FromString(m.payload).events) for m in msgs]
        assert sizes == [10, 10, 5]
        # Chunks preserve job order.
        ids = [
            e.submit_job.job_id
            for m in msgs
            for e in pb.EventSequence.FromString(m.payload).events
        ]
        assert ids == [f"j{i}" for i in range(25)]


def test_consumer_positions_and_ack(tmp_path):
    with EventLog(str(tmp_path / "log"), num_partitions=2) as log:
        publisher = Publisher(log)
        publisher.publish([submit_seq("qa", "js-a", ["a1"])])
        publisher.publish([submit_seq("qb", "js-b", ["b1"])])
        consumer = Consumer(log)
        batch = consumer.poll()
        got = {e.submit_job.job_id for s in batch.sequences for e in s.events}
        assert got == {"a1", "b1"}
        # Without ack, poll returns the same data (at-least-once).
        again = consumer.poll()
        assert {e.submit_job.job_id for s in again.sequences for e in s.events} == got
        consumer.ack(batch.next_positions)
        assert consumer.poll().sequences == []
        assert consumer.caught_up()
        # New data resumes from the stored positions.
        publisher.publish([submit_seq("qa", "js-a", ["a2"])])
        batch2 = consumer.poll()
        assert [
            e.submit_job.job_id for s in batch2.sequences for e in s.events
        ] == ["a2"]


def test_marker_fencing(tmp_path):
    with EventLog(str(tmp_path / "log"), num_partitions=3) as log:
        publisher = Publisher(log)
        publisher.publish([submit_seq("q", "js", ["before"])])
        group = publisher.publish_markers()
        publisher.publish([submit_seq("q", "js2", ["after"])])
        fenced = wait_for_markers({}, log, group)
        assert set(fenced) == {0, 1, 2}
        # Everything before the fence is at offsets < fenced position.
        consumer = Consumer(log)
        batch = consumer.poll()
        for msg, seq in zip(batch.messages, batch.sequences):
            for ev in seq.events:
                if ev.WhichOneof("event") == "submit_job":
                    if ev.submit_job.job_id == "before":
                        assert msg.offset < fenced[msg.partition]


def test_missing_marker_raises(tmp_path):
    with EventLog(str(tmp_path / "log"), num_partitions=1) as log:
        Publisher(log).publish([submit_seq("q", "js", ["x"])])
        with pytest.raises(TimeoutError):
            wait_for_markers({}, log, "no-such-group", timeout=0.1)


def test_partition_count_is_pinned(tmp_path):
    path = str(tmp_path / "log")
    with EventLog(path, num_partitions=4) as log:
        log.append(3, b"k", b"v")
    with pytest.raises(ValueError, match="4 partitions"):
        EventLog(path, num_partitions=2)


def test_oversized_record_read_grows_buffer(tmp_path):
    with EventLog(str(tmp_path / "log"), num_partitions=1) as log:
        big = b"x" * (1 << 16)
        log.append(0, b"k", big)
        msgs = log.read(0, 0, max_bytes=64)  # far smaller than the record
        assert len(msgs) == 1 and msgs[0].payload == big


def test_corrupt_body_detected(tmp_path):
    path = str(tmp_path / "log")
    with EventLog(path, num_partitions=1) as log:
        log.append(0, b"k", b"payload-one")
        log.append(0, b"k", b"payload-two")
        log.flush()
    # Flip a byte inside the first record's payload (below the recovered end).
    fpath = os.path.join(path, "p0.log")
    with open(fpath, "r+b") as f:
        f.seek(10)
        b = f.read(1)
        f.seek(10)
        f.write(bytes([b[0] ^ 0xFF]))
    # Reopen: MID-LOG corruption (more data after the bad record) is disk
    # damage, not crash residue -- silently truncating would vanish acked
    # records, so the open fails loudly instead (operator restores from a
    # replica or checkpoint).
    with pytest.raises(OSError, match="failed to open"):
        EventLog(path, num_partitions=1)


def test_torn_final_record_truncated_at_byte_boundary(tmp_path):
    """A crash mid-append tears the FINAL record at an arbitrary byte
    boundary; reopen must truncate exactly it and keep every prior record
    (the round-21 distinction: torn tail repairs, mid-log damage halts)."""
    path = str(tmp_path / "log")
    with EventLog(path, num_partitions=1) as log:
        log.append(0, b"k", b"first-record")
        good_end = log.end_offset(0)
        log.append(0, b"k", b"second-record-that-tears")
        torn_end = log.end_offset(0)
        log.flush()
    fpath = os.path.join(path, "p0.log")
    # Cut inside the last record's payload: the header is intact and sane,
    # but the declared extent runs past EOF.
    with open(fpath, "r+b") as f:
        f.truncate(torn_end - 7)
    with EventLog(path, num_partitions=1) as log:
        assert log.end_offset(0) == good_end
        assert [m.payload for m in log.read(0, 0)] == [b"first-record"]
        log.append(0, b"k", b"after-repair")
        assert [m.payload for m in log.read(0, 0)] == [
            b"first-record",
            b"after-repair",
        ]
    # A cut that leaves the full length but scrambles the final record's
    # trailing CRC bytes is the same crash shape (unordered sector loss):
    # still a tail repair, not a halt.
    with EventLog(path, num_partitions=1) as log:
        log.append(0, b"k", b"crc-torn")
        end = log.end_offset(0)
        log.flush()
    with open(fpath, "r+b") as f:
        f.seek(end - 2)
        f.write(b"\x00\x00")
    with EventLog(path, num_partitions=1) as log:
        assert [m.payload for m in log.read(0, 0)] == [
            b"first-record",
            b"after-repair",
        ]


def test_publish_does_not_mutate_input(tmp_path):
    with EventLog(str(tmp_path / "log"), num_partitions=1) as log:
        seq = submit_seq("q", "js", ["j1"])
        Publisher(log).publish([seq])
        assert seq.events[0].created_ns == 0  # caller's proto untouched
        stored = pb.EventSequence.FromString(log.read(0, 0)[0].payload)
        assert stored.events[0].created_ns > 0
