"""FakeClusterContext: an in-memory cluster with simulated pod lifecycle.

Equivalent of the reference's fake executor cluster
(internal/executor/fake/context/context.go:32-57,128): NodeSpec'd phantom
nodes, capacity-checked pod binding, and a pod lifecycle that advances
pending -> running -> succeeded.  Where the reference advances state with
goroutines and wall-clock sleeps, this fake is driven by an explicit virtual
clock (`tick`), so tests are deterministic and instant.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import numpy as np

from armada_tpu.core.resources import ResourceListFactory
from armada_tpu.core.types import JobSpec, NodeSpec
from armada_tpu.executor.cluster import PodPhase, PodState

DEFAULT_RUNTIME_S = 1.0
RUNTIME_ANNOTATION = "armada-tpu/runtime-s"


@dataclasses.dataclass
class _Pod:
    state: PodState
    requests: np.ndarray  # atoms
    start_at: float
    finish_at: float
    log: list = dataclasses.field(default_factory=list)
    # Materialised network objects (spec.services / spec.ingress): names the
    # fake "created", and port -> synthesized address for exposed ports.
    services: list = dataclasses.field(default_factory=list)
    ingresses: list = dataclasses.field(default_factory=list)
    addresses: dict = dataclasses.field(default_factory=dict)


class FakeClusterContext:
    """A simulated cluster: nodes + pods, advanced by tick(dt)."""

    def __init__(
        self,
        nodes: Sequence[NodeSpec],
        factory: ResourceListFactory,
        start_delay_s: float = 0.0,
        runtime_of: Optional[Callable[[JobSpec], float]] = None,
    ):
        self._nodes = {n.id: n for n in nodes}
        self._factory = factory
        self._start_delay = start_delay_s
        self._runtime_of = runtime_of or self._default_runtime
        self._pods: dict[str, _Pod] = {}
        self._allocated: dict[str, np.ndarray] = {
            n.id: np.zeros(factory.num_resources, np.int64) for n in nodes
        }
        self.now = 0.0

    @staticmethod
    def _default_runtime(spec: JobSpec) -> float:
        ann = getattr(spec, "annotations", None) or {}
        try:
            return float(ann.get(RUNTIME_ANNOTATION, DEFAULT_RUNTIME_S))
        except (TypeError, ValueError):
            return DEFAULT_RUNTIME_S

    # --- ClusterContext -----------------------------------------------------

    def submit_pod(
        self,
        run_id: str,
        job_id: str,
        queue: str,
        jobset: str,
        spec: JobSpec,
        node_id: str,
    ) -> None:
        if run_id in self._pods:
            return  # idempotent resubmission
        node = self._nodes.get(node_id)
        if node is None:
            raise ValueError(f"unknown node {node_id}")
        req = (
            spec.resources.atoms.astype(np.int64)
            if spec.resources is not None
            else np.zeros(self._factory.num_resources, np.int64)
        )
        total = (
            node.total_resources.atoms
            if node.total_resources is not None
            else np.zeros_like(req)
        )
        if np.any(self._allocated[node_id] + req > total):
            raise ValueError(
                f"node {node_id} has insufficient capacity for {job_id}"
            )
        self._allocated[node_id] += req
        runtime = self._runtime_of(spec)
        # Materialise the job's network objects like the kube adapter does
        # (executor/util/kubernetes_object.go): one Service per ServiceSpec,
        # one Ingress per IngressSpec, ingress ports resolving to
        # synthesized per-job hosts.
        services, ingresses, addresses = [], [], {}
        next_node_port = 30000 + (abs(hash(run_id)) % 1000)
        for i, sv in enumerate(getattr(spec, "services", ()) or ()):
            services.append(sv.name or f"armada-{run_id}-svc{i}")
        for i, ig in enumerate(getattr(spec, "ingress", ()) or ()):
            ingresses.append(f"armada-{run_id}-ing{i}")
            for port in ig.ports:
                addresses[int(port)] = f"{job_id}-{port}.fake.local"
        for sv in getattr(spec, "services", ()) or ():
            if sv.type == "NodePort":
                for port in sv.ports:
                    addresses.setdefault(
                        int(port), f"{node_id}:{next_node_port}"
                    )
                    next_node_port += 1
        self._pods[run_id] = _Pod(
            state=PodState(
                run_id=run_id,
                job_id=job_id,
                queue=queue,
                jobset=jobset,
                node_id=node_id,
                phase=PodPhase.PENDING,
            ),
            requests=req,
            start_at=self.now + self._start_delay,
            finish_at=self.now + self._start_delay + runtime,
            log=[f"[t={self.now:.1f}] pod created for job {job_id} on {node_id}"],
            services=services,
            ingresses=ingresses,
            addresses=addresses,
        )
        for name in services:
            self._pods[run_id].log.append(
                f"[t={self.now:.1f}] service {name} created"
            )
        for name in ingresses:
            self._pods[run_id].log.append(
                f"[t={self.now:.1f}] ingress {name} created"
            )

    def delete_pod(self, run_id: str) -> None:
        pod = self._pods.pop(run_id, None)
        if pod is not None and pod.state.phase in (
            PodPhase.PENDING,
            PodPhase.RUNNING,
        ):
            self._allocated[pod.state.node_id] -= pod.requests

    def node_specs(self) -> Sequence[NodeSpec]:
        return list(self._nodes.values())

    def pod_states(self) -> Sequence[PodState]:
        return [p.state for p in self._pods.values()]

    def queue_usage(self) -> dict[str, list[int]]:
        """Per-queue atoms of pending/running pods (the fake cluster's
        "usage" is the pods' requests, the same approximation the reference
        takes for pods without metrics,
        utilisation/cluster_utilisation.go getAllocatedResourceByNodeName)."""
        out: dict[str, list[int]] = {}
        for pod in self._pods.values():
            if pod.state.phase in (PodPhase.PENDING, PodPhase.RUNNING):
                prev = out.get(pod.state.queue)
                if prev is None:
                    out[pod.state.queue] = [int(a) for a in pod.requests]
                else:
                    for i, a in enumerate(pod.requests):
                        prev[i] += int(a)
        return out

    def usage_samples(self):
        """One sample per PENDING/RUNNING pod -- the payloads behind the
        ResourceUtilisation events (armadaevents oneof entry 17) and the
        executor pod metrics."""
        from armada_tpu.executor.cluster import UsageSample

        return [
            UsageSample(
                run_id=run_id,
                job_id=pod.state.job_id,
                queue=pod.state.queue,
                jobset=pod.state.jobset,
                node_id=pod.state.node_id,
                atoms=tuple(int(a) for a in pod.requests),
                phase=pod.state.phase.name,
            )
            for run_id, pod in self._pods.items()
            if pod.state.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        ]

    def get_pod(self, run_id: str) -> Optional[PodState]:
        pod = self._pods.get(run_id)
        return pod.state if pod else None

    def pod_network(self, run_id: str) -> dict[int, str]:
        """port -> reachable address of the run's exposed ports (ingress
        hosts + NodePort bindings) -- the payload behind the executor's
        StandaloneIngressInfo report.  {} = nothing exposed."""
        pod = self._pods.get(run_id)
        return dict(pod.addresses) if pod else {}

    def pod_network_objects(self, run_id: str) -> tuple[list, list]:
        """(service names, ingress names) the fake materialised -- cleanup
        and kind-e2e assertions."""
        pod = self._pods.get(run_id)
        return (list(pod.services), list(pod.ingresses)) if pod else ([], [])

    # --- simulation controls ------------------------------------------------

    def tick(self, dt: float = 0.0) -> None:
        """Advance virtual time; pods start and finish on schedule."""
        self.now += dt
        for pod in self._pods.values():
            if pod.state.phase is PodPhase.PENDING and self.now >= pod.start_at:
                pod.state.phase = PodPhase.RUNNING
                pod.log.append(f"[t={self.now:.1f}] container started")
            if pod.state.phase is PodPhase.RUNNING and self.now >= pod.finish_at:
                pod.state.phase = PodPhase.SUCCEEDED
                pod.log.append(f"[t={self.now:.1f}] exit 0")
                self._allocated[pod.state.node_id] -= pod.requests

    def set_pod_message(self, run_id: str, message: str) -> None:
        """Fault injection: attach a kubelet-style diagnostic (e.g. an image
        pull error) without changing phase -- feeds the pending-pod checks."""
        pod = self._pods[run_id]
        pod.state.message = message
        pod.log.append(f"[t={self.now:.1f}] {message}")

    def fail_pod(self, run_id: str, message: str = "injected failure") -> None:
        """Fault injection: flip a live pod to FAILED (pod_issue_handler tests)."""
        pod = self._pods[run_id]
        if pod.state.phase in (PodPhase.PENDING, PodPhase.RUNNING):
            self._allocated[pod.state.node_id] -= pod.requests
        pod.state.phase = PodPhase.FAILED
        pod.state.message = message
        pod.log.append(f"[t={self.now:.1f}] FAILED: {message}")

    # --- binoculars surface (logs + cordon) --------------------------------

    def pod_logs(self, run_id: str) -> str:
        """The pod's log text (reference: binoculars logs.go:43 reads via
        kube-api; the fake synthesizes lifecycle lines)."""
        pod = self._pods.get(run_id)
        if pod is None:
            raise KeyError(f"no pod for run {run_id}")
        return "\n".join(pod.log)

    def cordon_node(
        self, node_id: str, cordoned: bool = True, labels: Optional[dict] = None
    ) -> None:
        """Mark a node (un)schedulable + merge audit labels (binoculars
        cordon.go strategic-merge patch); the change propagates to the
        scheduler with the next snapshot."""
        import dataclasses as _dc

        node = self._nodes.get(node_id)
        if node is None:
            raise KeyError(f"unknown node {node_id}")
        merged = dict(node.labels)
        merged.update(labels or {})
        self._nodes[node_id] = _dc.replace(
            node, unschedulable=cordoned, labels=merged
        )
