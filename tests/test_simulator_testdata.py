"""The shipped simulator testdata parses and carries the expected shapes
(the reference ships testdata/{clusters,workloads}; ours lives in
testdata/simulator)."""

import glob

from armada_tpu.simulator import (
    cluster_spec_from_yaml,
    workload_spec_from_yaml,
)


def test_all_cluster_specs_parse():
    paths = sorted(glob.glob("testdata/simulator/clusters/*.yaml"))
    assert len(paths) >= 2
    specs = {p: cluster_spec_from_yaml(p) for p in paths}
    tiny = next(s for s in specs.values() if s.name == "tiny")
    assert tiny.clusters[0].node_templates[0].number == 4
    assert tiny.workflow_manager_delay.minimum_s == 1.0
    pools = next(s for s in specs.values() if s.name == "two-pools")
    assert {c.pool for c in pools.clusters} == {"cpu", "gpu"}
    gpu = next(c for c in pools.clusters if c.pool == "gpu")
    assert gpu.node_templates[0].labels == {"accelerator": "a100"}


def test_all_workload_specs_parse():
    paths = sorted(glob.glob("testdata/simulator/workloads/*.yaml"))
    assert len(paths) >= 2
    specs = {p: workload_spec_from_yaml(p) for p in paths}
    basic = next(s for s in specs.values() if s.name == "basic")
    assert {q.name for q in basic.queues} == {"alice", "bob"}
    assert basic.queues[0].job_templates[0].runtime.tail_mean_s == 15.0
    dag = next(s for s in specs.values() if s.name == "dag")
    train = next(
        t for q in dag.queues for t in q.job_templates if t.id == "train"
    )
    assert train.dependencies == ("prepare",)
    assert train.earliest_submit_time_from_dependency_completion_s == 10.0
