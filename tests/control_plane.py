"""A complete in-process control plane for tests and the testsuite runner.

The whole system wired together the way schedulerapp.go + server.go +
application.go wire the reference: event log, scheduler DB, event DB,
ingestion pipelines, submit server, event API, scheduler, executor-api and a
fake-executor fleet.
"""

from __future__ import annotations

import dataclasses

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import NodeSpec
from armada_tpu.eventlog import EventLog
from armada_tpu.eventlog.publisher import Publisher
from armada_tpu.executor import ExecutorService, FakeClusterContext
from armada_tpu.ingest.converter import convert_sequences
from armada_tpu.ingest.pipeline import IngestionPipeline
from armada_tpu.ingest.schedulerdb import SchedulerDb
from armada_tpu.jobdb.jobdb import JobDb
from armada_tpu.scheduler import (
    FairSchedulingAlgo,
    Scheduler,
    StandaloneLeaderController,
)
from armada_tpu.scheduler.api import ExecutorApi
from armada_tpu.server import (
    EventApi,
    EventDb,
    QueueRepository,
    SubmitServer,
    event_sink_converter,
)


class ManualClock:
    def __init__(self, t: float = 1_000_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@dataclasses.dataclass
class ControlPlane:
    config: SchedulingConfig
    clock: ManualClock
    log: EventLog
    db: SchedulerDb
    eventdb: EventDb
    publisher: Publisher
    scheduler_pipeline: IngestionPipeline
    event_pipeline: IngestionPipeline
    queues: QueueRepository
    server: SubmitServer
    event_api: EventApi
    jobdb: JobDb
    scheduler: Scheduler
    executor_api: ExecutorApi
    executors: list

    @staticmethod
    def build(
        tmp_path,
        config: SchedulingConfig | None = None,
        executor_specs: dict | None = None,
        runtime_s: float = 5.0,
        db_url: str | None = None,
    ) -> "ControlPlane":
        """executor_specs: {executor_id: (num_nodes, cpu, mem)}.
        db_url: external scheduler database (e.g. a postgres:// DSN); the
        default is embedded in-memory SQLite."""
        config = config or SchedulingConfig(shape_bucket=32, enable_assertions=True)
        clock = ManualClock()
        factory = config.resource_list_factory()
        # ARMADA_INGEST_SHARDS arms the partition-parallel ingest plane for
        # the whole harness (chaos_cycle --ingest-shards rides this), so
        # the integration suites exercise the sharded path when armed.
        from armada_tpu.ingest import resolve_num_shards

        shards = resolve_num_shards()
        # ARMADA_STORE_SHARDS additionally shards the materialized store
        # (ingest/storeunion.py; chaos_cycle --store-shards rides this):
        # one SQLite file per store shard under tmp_path, the ingest width
        # raised to a multiple so every shard's partitions live in one file.
        import os as _os

        try:
            store_shards = int(_os.environ.get("ARMADA_STORE_SHARDS", "0"))
        except ValueError:
            store_shards = 0
        if store_shards > 1:
            shards = max(shards, store_shards)
            shards += (-shards) % store_shards
        log = EventLog(str(tmp_path / "log"), num_partitions=max(2, shards))
        shards = min(shards, log.num_partitions)
        if store_shards > 1:
            from armada_tpu.ingest.storeunion import ShardedSchedulerDb

            db = ShardedSchedulerDb(
                db_url or str(tmp_path / "store-shards"),
                num_shards=store_shards,
                num_partitions=log.num_partitions,
            )
        else:
            db = SchedulerDb(db_url or ":memory:")
        eventdb = EventDb(":memory:")
        publisher = Publisher(log, clock=clock)
        if shards > 1:
            from armada_tpu.ingest import PartitionedIngestionPipeline

            scheduler_pipeline = PartitionedIngestionPipeline(
                log, db, convert_sequences, consumer_name="scheduler",
                num_shards=shards,
            )
            event_pipeline = PartitionedIngestionPipeline(
                log, eventdb, event_sink_converter, consumer_name="events",
                num_shards=shards,
            )
        else:
            scheduler_pipeline = IngestionPipeline(
                log, db, convert_sequences, consumer_name="scheduler"
            )
            event_pipeline = IngestionPipeline(
                log, eventdb, event_sink_converter, consumer_name="events"
            )
        queues = QueueRepository(db)
        server = SubmitServer(db, publisher, queues, config, clock=clock)
        jobdb = JobDb(config)
        feed = None
        if config.incremental_problem_build:
            from armada_tpu.scheduler.incremental_algo import IncrementalProblemFeed

            feed = IncrementalProblemFeed(config)
            feed.attach(jobdb)
        scheduler = Scheduler(
            db,
            jobdb,
            FairSchedulingAlgo(
                config,
                queues=queues.scheduling_queues,
                clock_ns=lambda: int(clock() * 1e9),
                feed=feed,
            ),
            publisher,
            StandaloneLeaderController(),
            config,
            clock=clock,
            ingest_step=scheduler_pipeline.run_until_caught_up,
        )
        executor_api = ExecutorApi(db, publisher, factory)
        executors = []
        for ex_id, (n, cpu, mem) in (executor_specs or {"ex1": (2, "8", "32")}).items():
            nodes = [
                NodeSpec(
                    id=f"{ex_id}-n{i}",
                    pool="default",
                    executor=ex_id,
                    total_resources=factory.from_mapping({"cpu": cpu, "memory": mem}),
                )
                for i in range(n)
            ]
            cluster = FakeClusterContext(
                nodes, factory, runtime_of=lambda s, r=runtime_s: r
            )
            executors.append(
                ExecutorService(ex_id, "default", cluster, executor_api, factory, clock=clock)
            )
        return ControlPlane(
            config=config,
            clock=clock,
            log=log,
            db=db,
            eventdb=eventdb,
            publisher=publisher,
            scheduler_pipeline=scheduler_pipeline,
            event_pipeline=event_pipeline,
            queues=queues,
            server=server,
            event_api=EventApi(eventdb),
            jobdb=jobdb,
            scheduler=scheduler,
            executor_api=executor_api,
            executors=executors,
        )

    # --- driving ------------------------------------------------------------

    def ingest(self) -> None:
        self.scheduler_pipeline.run_until_caught_up()
        self.event_pipeline.run_until_caught_up()

    def step(self, tick_s: float = 1.0) -> None:
        """One control-plane heartbeat: ingest, schedule, executors act."""
        self.ingest()
        self.scheduler.cycle()
        self.ingest()
        for ex in self.executors:
            ex.cluster.tick(tick_s)
            ex.run_once()
        self.clock.advance(tick_s)

    def run_until(self, predicate, max_steps: int = 200, tick_s: float = 1.0) -> int:
        """Step until predicate() or exhaustion; returns steps taken."""
        for i in range(max_steps):
            if predicate():
                return i
            self.step(tick_s)
        raise AssertionError(f"predicate not satisfied after {max_steps} steps")

    def job_states(self) -> dict:
        rows, _ = self.db.fetch_job_updates(0, 0)
        out = {}
        for r in rows:
            if r["succeeded"]:
                s = "succeeded"
            elif r["failed"]:
                s = "failed"
            elif r["cancelled"]:
                s = "cancelled"
            elif r["queued"]:
                s = "queued"
            else:
                s = "leased"
            out[r["job_id"]] = s
        return out

    def close(self) -> None:
        self.db.close()
        self.eventdb.close()
        self.log.close()
