"""Binoculars-lite tests: pod logs + node cordon next to the cluster.

Modeled on the reference's binoculars service (internal/binoculars/service/
logs.go, cordon.go): logs come straight from the cluster; cordoning a node
stops new placements there while running pods finish.
"""

import grpc
import pytest

from armada_tpu.executor.binoculars import Binoculars
from armada_tpu.rpc.client import BinocularsClient
from armada_tpu.rpc.server import make_server
from armada_tpu.server import JobSubmitItem, QueueRecord
from tests.control_plane import ControlPlane


@pytest.fixture
def stack(tmp_path):
    cp = ControlPlane.build(tmp_path, runtime_s=5.0)
    cp.server.create_queue(QueueRecord("q"))
    cluster = cp.executors[0].cluster
    server, port = make_server(binoculars=Binoculars(cluster))
    client = BinocularsClient(f"127.0.0.1:{port}")
    yield cp, cluster, client
    client.close()
    server.stop(None)
    cp.close()


def item(cpu="2"):
    return JobSubmitItem(resources={"cpu": cpu, "memory": "2"})


def test_logs_over_wire(stack):
    cp, cluster, client = stack
    (jid,) = cp.server.submit_jobs("q", "js", [item()])
    for ex in cp.executors:
        ex.run_once()
    cp.step()
    cluster.tick(1.0)

    log = client.logs(job_id=jid)
    assert "pod created for job" in log
    assert "container started" in log

    (pod,) = cluster.pod_states()
    assert client.logs(run_id=pod.run_id) == log

    with pytest.raises(grpc.RpcError) as e:
        client.logs(job_id="ghost")
    assert e.value.code() == grpc.StatusCode.NOT_FOUND


def test_failed_pod_log_carries_reason(stack):
    cp, cluster, client = stack
    (jid,) = cp.server.submit_jobs("q", "js", [item()])
    for ex in cp.executors:
        ex.run_once()
    cp.step()
    (pod,) = cluster.pod_states()
    cluster.fail_pod(pod.run_id, "disk exploded")
    assert "FAILED: disk exploded" in client.logs(job_id=jid)


def test_cordon_stops_new_placements(stack):
    cp, cluster, client = stack
    nodes = [n.id for n in cluster.node_specs()]
    client.cordon(nodes[0])
    assert next(
        n for n in cluster.node_specs() if n.id == nodes[0]
    ).unschedulable

    # snapshot propagates on the next heartbeat; everything lands on node 1
    ids = cp.server.submit_jobs("q", "js", [item() for _ in range(3)])
    for ex in cp.executors:
        ex.run_once()
    cp.step()
    placed = {p.node_id for p in cluster.pod_states()}
    assert placed == {nodes[1]}

    # uncordon restores the node
    client.uncordon(nodes[0])
    cp.server.submit_jobs("q", "js2", [item() for _ in range(3)])
    cp.step()
    cp.step()
    placed = {p.node_id for p in cluster.pod_states()}
    assert nodes[0] in placed

    with pytest.raises(grpc.RpcError):
        client.cordon("no-such-node")
