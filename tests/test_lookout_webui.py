"""Lookout web UI: dashboard page + JSON API over the lookout query stack
(the internal/lookoutui equivalent surface)."""

import json
import urllib.error
import urllib.request

import pytest

from armada_tpu.ingest.pipeline import IngestionPipeline
from armada_tpu.lookout import LookoutDb, LookoutQueries, lookout_converter
from armada_tpu.lookout.webui import LookoutWebUI, STATE_ORDER
from armada_tpu.server import JobSubmitItem, QueueRecord
from tests.control_plane import ControlPlane


@pytest.fixture
def world(tmp_path):
    plane = ControlPlane.build(tmp_path)
    plane.server.create_queue(QueueRecord("qa"))
    plane.server.create_queue(QueueRecord("qb"))
    lookoutdb = LookoutDb(":memory:")
    pipeline = IngestionPipeline(
        plane.log, lookoutdb, lookout_converter, consumer_name="lookout"
    )
    ui = LookoutWebUI(LookoutQueries(lookoutdb))
    yield plane, pipeline, ui
    ui.stop()
    lookoutdb.close()
    plane.close()


def get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        ctype = r.headers.get("Content-Type", "")
        body = r.read().decode()
    return (json.loads(body) if "json" in ctype else body)


def populate(plane, pipeline):
    ids_a = plane.server.submit_jobs(
        "qa", "js1", [JobSubmitItem(resources={"cpu": "2", "memory": "2"})] * 3
    )
    ids_b = plane.server.submit_jobs(
        "qb", "js2", [JobSubmitItem(resources={"cpu": "2", "memory": "2"})] * 2
    )
    plane.executors[0].run_once()
    pipeline.run_until_caught_up()
    plane.ingest()
    plane.scheduler.cycle()
    pipeline.run_until_caught_up()
    return ids_a, ids_b


def test_page_serves_app(world):
    plane, pipeline, ui = world
    page = get(ui.port, "/")
    assert "armada-tpu lookout" in page
    # state identity is never color-alone: names appear as text options/labels
    for state in STATE_ORDER:
        assert state.lower() in page or state in page


def test_jobs_api_filters_and_pagination(world):
    plane, pipeline, ui = world
    ids_a, ids_b = populate(plane, pipeline)
    out = get(ui.port, "/api/jobs")
    assert out["total"] == 5
    qa = get(ui.port, "/api/jobs?queue=qa")
    assert qa["total"] == 3 and all(j["queue"] == "qa" for j in qa["jobs"])
    page = get(ui.port, "/api/jobs?take=2&skip=2&order=job_id&dir=ASC")
    assert len(page["jobs"]) == 2 and page["total"] == 5
    leased = get(ui.port, "/api/jobs?state=LEASED")
    assert leased["total"] == 5  # all leased after the cycle


def test_groups_and_overview(world):
    plane, pipeline, ui = world
    populate(plane, pipeline)
    groups = get(ui.port, "/api/groups?by=queue")["groups"]
    assert {g["group"]: g["count"] for g in groups} == {"qa": 3, "qb": 2}
    assert groups[0]["states"]["LEASED"] == 3
    overview = get(ui.port, "/api/overview")
    assert overview["states"] == {"LEASED": 5}


def test_job_details_with_runs(world):
    plane, pipeline, ui = world
    ids_a, _ = populate(plane, pipeline)
    d = get(ui.port, f"/api/job/{ids_a[0]}")
    assert d["job_id"] == ids_a[0] and d["state"] == "LEASED"
    assert len(d["runs"]) == 1 and d["runs"][0]["node"]


def test_bad_requests_are_400(world):
    plane, pipeline, ui = world
    with pytest.raises(urllib.error.HTTPError) as e:
        get(ui.port, "/api/groups?by=not_a_field")
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        get(ui.port, "/api/jobs?order=nope")
    assert e.value.code == 400


def test_serve_hosts_the_ui(tmp_path):
    from armada_tpu.cli.serve import start_control_plane

    plane = start_control_plane(
        str(tmp_path), cycle_interval_s=0.2, schedule_interval_s=0.5,
        lookout_port=0,
    )
    try:
        page = get(plane.lookout_web.port, "/")
        assert "armada-tpu lookout" in page
        assert get(plane.lookout_web.port, "/api/overview") == {"states": {}}
    finally:
        plane.stop()


def test_take_clamped_and_unknown_job_404(world):
    plane, pipeline, ui = world
    populate(plane, pipeline)
    out = get(ui.port, "/api/jobs?take=-1")
    assert len(out["jobs"]) >= 1  # LIMIT -1 would also 'work'; check clamping:
    assert len(out["jobs"]) == 1  # take=-1 clamps to 1
    with pytest.raises(urllib.error.HTTPError) as e:
        get(ui.port, "/api/job/no-such-job")
    assert e.value.code == 404


def req(port, path, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=data,
        method=method,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(r, timeout=5) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_saved_views_are_server_side(world):
    """Saved views persist in the lookout DB (the reference UI's
    server-backed views), not the browser."""
    plane, pipeline, ui = world
    assert get(ui.port, "/api/views") == {"views": []}
    st, _ = req(ui.port, "/api/views", "POST",
                {"name": "prod-fails", "payload": {"f-queue": "qa", "f-state": "FAILED"}})
    assert st == 200
    views = get(ui.port, "/api/views")["views"]
    assert [v["name"] for v in views] == ["prod-fails"]
    assert json.loads(views[0]["payload"])["f-queue"] == "qa"
    # upsert overwrites
    req(ui.port, "/api/views", "POST",
        {"name": "prod-fails", "payload": {"f-queue": "qb"}})
    views = get(ui.port, "/api/views")["views"]
    assert len(views) == 1 and json.loads(views[0]["payload"])["f-queue"] == "qb"
    st, _ = req(ui.port, "/api/views/prod-fails", "DELETE")
    assert st == 200
    assert get(ui.port, "/api/views") == {"views": []}
    st, _ = req(ui.port, "/api/views/missing", "DELETE")
    assert st == 404
    st, _ = req(ui.port, "/api/views", "POST", {"name": "", "payload": {}})
    assert st == 400


def test_logs_endpoint_without_binoculars_is_501(world):
    plane, pipeline, ui = world
    st, body = req(ui.port, "/api/logs?job=x&run=y")
    assert st == 501 and "binoculars" in body["error"]


def test_logs_endpoint_serves_pod_logs(tmp_path):
    """queue -> job -> runs -> logs without the CLI: the UI fetches pod logs
    through a binoculars logs callable (binoculars logs.go:39-43)."""
    plane = ControlPlane.build(tmp_path)
    plane.server.create_queue(QueueRecord("qa"))
    lookoutdb = LookoutDb(":memory:")
    pipeline = IngestionPipeline(
        plane.log, lookoutdb, lookout_converter, consumer_name="lookout"
    )

    def logs_of(job_id="", run_id=""):
        if run_id == "gone" or job_id == "gone":
            raise KeyError(f"no pod for {job_id or run_id}")
        return f"log line for {job_id or run_id}\n"

    ui = LookoutWebUI(LookoutQueries(lookoutdb), logs_of=logs_of)
    try:
        (jid,) = plane.server.submit_jobs(
            "qa", "js1", [JobSubmitItem(resources={"cpu": "1", "memory": "1"})]
        )
        plane.executors[0].run_once()
        pipeline.run_until_caught_up()
        st, body = req(ui.port, f"/api/logs?job={jid}")
        assert st == 200 and jid in body["log"]
        st, body = req(ui.port, "/api/logs?job=gone")
        assert st == 404
    finally:
        ui.stop()
        lookoutdb.close()
        plane.close()


def test_serve_wires_binoculars_log_viewer(tmp_path):
    """serve --binoculars-url: the control plane's lookout UI reaches a
    cluster's binoculars service over gRPC for the log viewer."""
    from armada_tpu.rpc.server import make_server
    from armada_tpu.cli.serve import start_control_plane

    class _Logs:
        def logs(self, job_id="", run_id=""):
            if job_id == "ghost":
                raise KeyError("no pod for job ghost")
            return f"hello from {job_id or run_id}"

    bserver, bport = make_server(binoculars=_Logs())
    plane = start_control_plane(
        str(tmp_path), cycle_interval_s=0.2, schedule_interval_s=0.5,
        lookout_port=0, binoculars_url=f"127.0.0.1:{bport}",
    )
    try:
        st, body = req(plane.lookout_web.port, "/api/logs?job=j123")
        assert st == 200 and body["log"] == "hello from j123"
        st, body = req(plane.lookout_web.port, "/api/logs?job=ghost")
        # gRPC NOT_FOUND surfaces as an upstream error, not a UI crash
        assert st in (404, 502) and "ghost" in body["error"]
    finally:
        plane.stop()
        bserver.stop(None)


def test_ui_gated_by_authenticator_chain():
    """The UI page and its JSON API gate on the same authn chain as the
    gRPC/REST transports (401 + Basic challenge for browsers); the dev
    default (no chain) stays open -- VERDICT r2's 'spoofable identity'
    posture closed for the last unauthenticated surface."""
    import base64

    from armada_tpu.server.authn import BasicAuthenticator, MultiAuthenticator

    lookoutdb = LookoutDb(":memory:")
    chain = MultiAuthenticator(
        [BasicAuthenticator({"ops": ("secret", ("sre",))})]
    )
    ui = LookoutWebUI(LookoutQueries(lookoutdb), authenticator=chain)
    try:
        # no credentials: 401 with a browser Basic challenge, on the page
        # and the API alike
        for path in ("/", "/api/overview", "/api/views"):
            req_obj = urllib.request.Request(f"http://127.0.0.1:{ui.port}{path}")
            try:
                urllib.request.urlopen(req_obj, timeout=5)
                assert False, f"{path} served without credentials"
            except urllib.error.HTTPError as e:
                assert e.code == 401
                assert "Basic" in e.headers.get("WWW-Authenticate", "")
        # wrong password: still 401
        bad = base64.b64encode(b"ops:wrong").decode()
        req_obj = urllib.request.Request(
            f"http://127.0.0.1:{ui.port}/api/overview",
            headers={"Authorization": f"Basic {bad}"},
        )
        try:
            urllib.request.urlopen(req_obj, timeout=5)
            assert False, "wrong password accepted"
        except urllib.error.HTTPError as e:
            assert e.code == 401
        # right credentials: the app serves
        good = base64.b64encode(b"ops:secret").decode()
        req_obj = urllib.request.Request(
            f"http://127.0.0.1:{ui.port}/",
            headers={"Authorization": f"Basic {good}"},
        )
        with urllib.request.urlopen(req_obj, timeout=5) as r:
            assert "armada-tpu lookout" in r.read().decode()
    finally:
        ui.stop()
        lookoutdb.close()


def test_action_endpoints_require_a_submit_server(world):
    plane, pipeline, ui = world  # fixture wires no submit server
    st, body = req(ui.port, "/api/jobs/cancel", "POST",
                   {"queue": "qa", "jobset": "js1", "job_ids": ["x"]})
    assert st == 501 and "read-only" in body["error"]


def test_ui_cancel_and_reprioritize_actions(tmp_path):
    """Operator actions from the SPA (the reference UI's CancelDialog /
    ReprioritiseDialog) ride the SAME SubmitServer as the gRPC verbs."""
    plane = ControlPlane.build(tmp_path)
    plane.server.create_queue(QueueRecord("qa"))
    lookoutdb = LookoutDb(":memory:")
    pipeline = IngestionPipeline(
        plane.log, lookoutdb, lookout_converter, consumer_name="lookout"
    )
    ui = LookoutWebUI(LookoutQueries(lookoutdb), submit=plane.server)
    try:
        ids = plane.server.submit_jobs(
            "qa", "js1",
            [JobSubmitItem(resources={"cpu": "1", "memory": "1"})] * 2,
        )
        pipeline.run_until_caught_up()

        st, body = req(ui.port, "/api/jobs/reprioritize", "POST",
                       {"queue": "qa", "jobset": "js1",
                        "job_ids": [ids[0]], "priority": 7})
        assert st == 200, body
        st, body = req(ui.port, "/api/jobs/cancel", "POST",
                       {"queue": "qa", "jobset": "js1",
                        "job_ids": [ids[1]], "reason": "ui test"})
        assert st == 200, body
        plane.ingest()
        plane.scheduler.cycle()
        pipeline.run_until_caught_up()
        d0 = get(ui.port, f"/api/job/{ids[0]}")
        d1 = get(ui.port, f"/api/job/{ids[1]}")
        assert d0["priority"] == 7
        assert d1["state"] == "CANCELLED"
        # unknown queue surfaces as a client error, not a 500
        st, body = req(ui.port, "/api/jobs/cancel", "POST",
                       {"queue": "nope", "jobset": "x", "job_ids": ["y"]})
        assert st in (400, 404)
    finally:
        ui.stop()
        lookoutdb.close()
        plane.close()


def test_reprioritize_rejects_empty_job_ids(tmp_path):
    """Empty job_ids means JOBSET-wide to SubmitServer; the per-job UI
    endpoint must never widen a click into a mass action."""
    plane = ControlPlane.build(tmp_path)
    plane.server.create_queue(QueueRecord("qa"))
    lookoutdb = LookoutDb(":memory:")
    ui = LookoutWebUI(LookoutQueries(lookoutdb), submit=plane.server)
    try:
        for path in ("/api/jobs/reprioritize", "/api/jobs/cancel"):
            st, body = req(ui.port, path, "POST",
                           {"queue": "qa", "jobset": "js", "priority": 1,
                            "job_ids": []})
            assert st == 400 and "non-empty" in body["error"], (path, body)
    finally:
        ui.stop()
        lookoutdb.close()
        plane.close()


def test_jobset_mass_actions(tmp_path):
    """Jobset-wide cancel/reprioritise (the reference UI's
    CancelJobSetsDialog / ReprioritizeJobSetsDialog) -- the deliberate
    mass-action endpoints, distinct from the per-job ones."""
    plane = ControlPlane.build(tmp_path)
    plane.server.create_queue(QueueRecord("qa"))
    lookoutdb = LookoutDb(":memory:")
    pipeline = IngestionPipeline(
        plane.log, lookoutdb, lookout_converter, consumer_name="lookout"
    )
    ui = LookoutWebUI(LookoutQueries(lookoutdb), submit=plane.server)
    try:
        ids = plane.server.submit_jobs(
            "qa", "massjs",
            [JobSubmitItem(resources={"cpu": "1", "memory": "1"})] * 3,
        )
        st, body = req(ui.port, "/api/jobsets/reprioritize", "POST",
                       {"queue": "qa", "jobset": "massjs", "priority": 9})
        assert st == 200, body
        st, body = req(ui.port, "/api/jobsets/cancel", "POST",
                       {"queue": "qa", "jobset": "massjs"})
        assert st == 200, body
        plane.ingest()
        plane.scheduler.cycle()
        pipeline.run_until_caught_up()
        for jid in ids:
            d = get(ui.port, f"/api/job/{jid}")
            assert d["state"] == "CANCELLED", d
            assert d["priority"] == 9
    finally:
        ui.stop()
        lookoutdb.close()
        plane.close()
