"""Multi-chip evidence at scale (VERDICT r3 weak #2): the 100k-gang x
5k-node round, single-device vs the full virtual mesh, with per-phase
timings and the result-equality check -- the recorded artifact beside
__graft_entry__.dryrun_multichip's tiny-shape compile check.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/multichip_scale.py [out.json]

Round 12 adds the STEADY-CYCLE leg: the same delta-driven cycle the
serving plane runs (incremental builder -> slab delta -> sharded scatter
apply -> sharded kernel -> compact decode) through the mesh serving
subsystem's MeshDeviceDeltaCache, A/B'd against the single-device
DeviceDeltaCache per cycle -- cold full-problem rounds alone say nothing
about the path production takes.  ARMADA_SCALE_STEADY_{JOBS,NODES,CYCLES}
downscale.

On the virtual CPU mesh the numbers measure CORRECTNESS + compiled
collective overhead on one physical socket (expect slower than single);
on a real v5e-8 the same program's node-axis reductions ride ICI.
docs/bench.md + docs/multichip.md carry the analysis.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _steady_cycle_ab() -> dict:
    """Delta-driven steady cycles: MeshDeviceDeltaCache (8-dev node-sharded
    slab) vs DeviceDeltaCache, decisions compared exactly every cycle."""
    from armada_tpu.core.types import RunningJob
    from armada_tpu.models import decode_result, schedule_round
    from armada_tpu.models.incremental import IncrementalBuilder
    from armada_tpu.models.slab import DeviceDeltaCache
    from armada_tpu.models.synthetic import synthetic_world
    from armada_tpu.models.xfer import TRANSFER_STATS
    from armada_tpu.parallel.mesh_slab import MeshDeviceDeltaCache
    from armada_tpu.parallel.serving import mesh_serving

    num_jobs = int(os.environ.get("ARMADA_SCALE_STEADY_JOBS", 100_000))
    num_nodes = int(os.environ.get("ARMADA_SCALE_STEADY_NODES", 5_000))
    cycles = int(os.environ.get("ARMADA_SCALE_STEADY_CYCLES", 3))
    burst = 500
    mesh_serving().configure(8)
    try:
        config, nodes, queues, specs, running, spec_factory = synthetic_world(
            num_nodes=num_nodes,
            num_jobs=num_jobs,
            num_queues=32,
            num_runs=num_nodes // 2,
            seed=11,
        )

        def build():
            b = IncrementalBuilder(config, "default", queues)
            b.set_nodes(nodes)
            b.submit_many(specs)
            for r in running:
                b.lease(r)
            return b

        arms = {
            "single": (build(), DeviceDeltaCache()),
            "mesh": (build(), MeshDeviceDeltaCache()),
        }
        spec_of = {s.id: s for s in specs}
        identical = True
        times = {"single": [], "mesh": []}
        xfer = {}
        for cyc in range(cycles + 1):  # cycle 0 = compile + full upload
            outs = {}
            for arm, (b, cache) in arms.items():
                TRANSFER_STATS.reset()
                t0 = time.perf_counter()
                bundle, ctx = b.assemble_delta()
                dev = cache.apply(bundle)
                res = schedule_round(
                    dev,
                    num_levels=len(ctx.ladder) + 2,
                    max_slots=ctx.max_slots,
                    slot_width=ctx.slot_width,
                )
                outs[arm] = decode_result(res, ctx)
                if cyc > 0:
                    times[arm].append(time.perf_counter() - t0)
                    xfer[arm] = TRANSFER_STATS.snapshot()
            a, m = outs["single"], outs["mesh"]
            if a.scheduled != m.scheduled or a.preempted != m.preempted:
                identical = False
                print(f"steady cycle {cyc} DIVERGED", file=sys.stderr)
            fresh = spec_factory(burst, 1000.0 + cyc)
            for s in fresh:
                spec_of[s.id] = s
            for arm, (b, _cache) in arms.items():
                b.remove_many(a.scheduled.keys())
                b.lease_many(
                    [
                        RunningJob(job=spec_of[j], node_id=n)
                        for j, n in a.scheduled.items()
                        if j in spec_of
                    ]
                )
                for jid in a.preempted:
                    b.unlease(jid)
                b.submit_many(fresh)
        out = {
            "shape": {"num_jobs": num_jobs, "num_nodes": num_nodes, "burst": burst},
            "cycles": cycles,
            "identical": identical,
            "xfer_single": xfer.get("single", {}),
            "xfer_mesh": xfer.get("mesh", {}),
        }
        # cycles=0 runs only the compile/upload cycle (equality still
        # checked) -- no timed steady cycles to report.
        if times["single"] and times["mesh"]:
            out["cycle_single_s"] = round(min(times["single"]), 4)
            out["cycle_mesh_s"] = round(min(times["mesh"]), 4)
        return out
    finally:
        mesh_serving().configure(0)


def main(out_path: str = "MULTICHIP_SCALE.json") -> int:
    sys.path.insert(0, ".")
    import __graft_entry__ as graft

    graft._pin_virtual_cpu_mesh(8)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from armada_tpu.models import SchedulingProblem, schedule_round
    from armada_tpu.models.synthetic import synthetic_problem
    from armada_tpu.parallel import (
        make_mesh,
        shard_problem,
        sharded_schedule_round,
    )

    shape = dict(
        num_nodes=5_000,
        num_gangs=100_000,
        num_queues=32,
        num_runs=2_500,
        global_burst=500,
        perq_burst=500,
        seed=11,
    )
    t0 = time.perf_counter()
    problem, meta = synthetic_problem(**shape)
    t_build = time.perf_counter() - t0
    kw = dict(
        num_levels=meta["num_levels"],
        max_slots=meta["max_slots"],
        slot_width=meta["slot_width"],
    )

    # --- single device -----------------------------------------------------
    t0 = time.perf_counter()
    dev = SchedulingProblem(*(jnp.asarray(a) for a in problem))
    jax.block_until_ready(dev)
    t_upload_single = time.perf_counter() - t0
    t0 = time.perf_counter()
    single = schedule_round(dev, **kw)
    jax.block_until_ready(single)
    t_compile_single = time.perf_counter() - t0
    t0 = time.perf_counter()
    single = schedule_round(dev, **kw)
    jax.block_until_ready(single)
    t_single = time.perf_counter() - t0

    # --- device meshes -----------------------------------------------------
    # Three factorizations of the 8 virtual devices: pure node-axis sharding
    # plus two jobs-axis splits -- the "data-parallel analog" half of the
    # mesh story (parallel/mesh.py:10-13; VERDICT r4 weak #2 asked for
    # at-scale bit-identity evidence beyond {nodes:8, jobs:1}).
    mesh_shapes = [(8, 1), (4, 2), (2, 4)]
    identical = True
    meshes_out = []
    for node_shards, job_shards in mesh_shapes:
        mesh = make_mesh(node_shards=node_shards, job_shards=job_shards)
        t0 = time.perf_counter()
        placed = shard_problem(problem, mesh)
        jax.block_until_ready(placed)
        t_shard = time.perf_counter() - t0
        t0 = time.perf_counter()
        sharded = sharded_schedule_round(placed, mesh, **kw)
        jax.block_until_ready(sharded)
        t_compile_sharded = time.perf_counter() - t0
        t0 = time.perf_counter()
        sharded = sharded_schedule_round(placed, mesh, **kw)
        jax.block_until_ready(sharded)
        t_sharded = time.perf_counter() - t0

        mesh_identical = True
        for name in (
            "g_state", "slot_gang", "slot_nodes", "slot_counts", "n_slots",
            "run_evicted", "run_rescheduled", "q_alloc", "iterations",
            "termination", "scheduled_count", "spot_price",
        ):
            a = np.asarray(getattr(single, name))
            b = np.asarray(getattr(sharded, name))
            if not np.array_equal(a, b):
                mesh_identical = False
                print(
                    f"mesh {node_shards}x{job_shards} DIVERGED on {name}",
                    file=sys.stderr,
                )
        identical = identical and mesh_identical
        meshes_out.append(
            {
                "mesh": {"nodes": node_shards, "jobs": job_shards},
                "identical": mesh_identical,
                "shard_place_s": round(t_shard, 4),
                "compile_sharded_s": round(t_compile_sharded, 4),
                "round_sharded_s": round(t_sharded, 4),
            }
        )
        print(
            f"mesh nodes={node_shards} jobs={job_shards}: "
            f"identical={mesh_identical} round={t_sharded:.3f}s",
            flush=True,
        )

    # --- steady cycle (the serving plane's actual path) --------------------
    print("steady-cycle A/B (delta-driven, mesh slab cache)...", flush=True)
    steady = _steady_cycle_ab()
    identical = identical and steady["identical"]
    print(
        f"steady cycle: identical={steady['identical']} "
        f"single={steady.get('cycle_single_s', 'n/a')}s "
        f"mesh={steady.get('cycle_mesh_s', 'n/a')}s",
        flush=True,
    )

    n_devices = 8
    doc = {
        "shape": shape,
        "devices": n_devices,
        "identical": identical,
        "steady_cycle": steady,
        "scheduled": int(np.asarray(single.scheduled_count)),
        "iterations": int(np.asarray(single.iterations)),
        "single_phases_s": {
            "problem_build_host": round(t_build, 4),
            "upload_single": round(t_upload_single, 4),
            "compile_single": round(t_compile_single, 4),
            "round_single": round(t_single, 4),
        },
        "meshes": meshes_out,
        "note": (
            "virtual CPU mesh: all 8 'devices' share one socket, so the "
            "sharded wall-clock measures SPMD correctness + compiled "
            "collective overhead, not speedup; on a v5e-8 the node-axis "
            "reductions ride ICI (see docs/bench.md multi-chip section).  "
            "Every mesh factorization must be bit-identical to the "
            "single-device round -- sharding only distributes reductions."
        ),
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc["single_phases_s"]))
    print(
        f"identical={identical} scheduled={doc['scheduled']} -> {out_path}"
    )
    return 0 if identical else 1


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:2]))
