"""Bounded-replay restart: snapshot-restore + suffix replay == full replay.

The acceptance pin for ISSUE 7's tentpole: restarting from a checkpoint
must replay ONLY the log suffix past the fence (replayed-event count
asserted, not timed) and reproduce full-replay state bit-equal -- JobDb
contents AND next-cycle scheduling decisions -- across submit/lease/
cancel/gang churn from the loadgen mix, over multiple seeds.  Plus the
promotion crash drill (leader_promote) and the `serve` restore path end to
end (wiped store -> checkpoint restore -> suffix replay -> serving).
"""

from __future__ import annotations

import os

import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.ingest.converter import convert_sequences
from armada_tpu.ingest.pipeline import IngestionPipeline
from armada_tpu.ingest.schedulerdb import SchedulerDb
from armada_tpu.jobdb.jobdb import JobDb
from armada_tpu.loadgen.workload import (
    CancelOp,
    MixConfig,
    ReprioritizeOp,
    SubmitOp,
    WorkloadGenerator,
)
from armada_tpu.scheduler.checkpoint import restore_plane, snapshot_plane
from armada_tpu.scheduler.reconciliation import apply_rows
from armada_tpu.server.queues import QueueRecord
from tests.control_plane import ControlPlane


def _apply_ops(plane: ControlPlane, gen: WorkloadGenerator, ops, jobset: str):
    for op in ops:
        if isinstance(op, SubmitOp):
            ids = plane.server.submit_jobs(op.queue, jobset, op.items)
            gen.note_submitted(op.queue, ids)
        elif isinstance(op, CancelOp):
            plane.server.cancel_jobs(op.queue, jobset, op.job_ids, reason="churn")
        elif isinstance(op, ReprioritizeOp):
            plane.server.reprioritize_jobs(
                op.queue, jobset, op.priority, job_ids=op.job_ids
            )


def _canon_jobs(db: SchedulerDb, config: SchedulingConfig) -> dict:
    """Canonical JobDb state rebuilt from a scheduler store, as plain
    tuples (bit-equality surface for the A/B restart comparison)."""
    jdb = JobDb(config)
    txn = jdb.write_txn()
    apply_rows(txn, *db.fetch_job_updates(0, 0), config)
    txn.commit()
    out = {}
    for job in jdb.read_txn().all_jobs():
        out[job.id] = (
            job.queue,
            job.priority,
            job.submitted_ns,
            job.queued,
            job.queued_version,
            job.validated,
            job.pools,
            job.cancel_requested,
            job.cancel_by_jobset_requested,
            job.preempt_requested,
            job.cancelled,
            job.succeeded,
            job.failed,
            tuple(
                (
                    r.id, r.node_id, r.pool, r.leased, r.pending, r.running,
                    r.succeeded, r.failed, r.cancelled, r.preempted,
                    r.returned, r.run_attempted, r.preempt_requested,
                    r.running_ns,
                )
                for r in job.runs
            ),
        )
    return out


def _decisions_of(db: SchedulerDb, config: SchedulingConfig, now_s: float):
    """One scheduling round's decisions straight off a store: rebuild the
    JobDb (through the incremental feed, so the runs-first lease path is
    exercised on the restore side too), snapshot executors, schedule."""
    import dataclasses as _dc

    from armada_tpu.scheduler import FairSchedulingAlgo
    from armada_tpu.scheduler.executors import ExecutorSnapshot
    from armada_tpu.scheduler.incremental_algo import IncrementalProblemFeed
    from armada_tpu.server.queues import QueueRepository

    cfg = _dc.replace(config, incremental_problem_build=True)
    factory = cfg.resource_list_factory()
    jdb = JobDb(cfg)
    feed = IncrementalProblemFeed(cfg)
    feed.attach(jdb)
    txn = jdb.write_txn()
    apply_rows(txn, *db.fetch_job_updates(0, 0), cfg)
    txn.commit()
    executors = [
        ExecutorSnapshot.from_json(row["snapshot"], factory)
        for row in db.executors()
    ]
    algo = FairSchedulingAlgo(
        cfg,
        queues=QueueRepository(db).scheduling_queues,
        clock_ns=lambda: int(now_s * 1e9),
        feed=feed,
    )
    txn = jdb.write_txn()
    try:
        result = algo.schedule(txn, executors, int(now_s * 1e9))
    finally:
        txn.abort()
    return (
        sorted((job.id, run.node_id) for job, run in result.scheduled),
        sorted(job.id for job, _run in result.preempted),
    )


def _log_messages_from(log, positions: dict) -> int:
    return sum(
        len(list(log.iter_from(p, positions.get(p, 0))))
        for p in range(log.num_partitions)
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_snapshot_restore_plus_suffix_replay_bit_equal_full_replay(
    tmp_path, seed
):
    plane = ControlPlane.build(tmp_path)
    config = plane.config
    jobset = f"rr-{seed}"
    mix = MixConfig(
        num_queues=2,
        queue_prefix=f"rr{seed}",
        jobset=jobset,
        gang_fraction=0.15,
    )
    gen = WorkloadGenerator(mix, seed=seed)
    for q in gen.queues:
        plane.server.create_queue(QueueRecord(q))
    try:
        # churn: submits/cancels/reprioritisations/gangs, with real
        # scheduling cycles leasing + finishing jobs in between
        for _ in range(6):
            _apply_ops(plane, gen, gen.next_ops(10), jobset)
            plane.step()
        snapshot = snapshot_plane(plane.db)  # the mid-point fence
        for _ in range(4):
            _apply_ops(plane, gen, gen.next_ops(10), jobset)
            plane.step()
        plane.ingest()

        # --- A: full replay from offset zero --------------------------------
        db_a = SchedulerDb(":memory:")
        total = IngestionPipeline(
            plane.log, db_a, convert_sequences, consumer_name="scheduler"
        ).run_until_caught_up()

        # --- B: snapshot restore + suffix-only replay ------------------------
        db_b = SchedulerDb(":memory:")
        restore_plane(snapshot, db_b)
        replayed = IngestionPipeline(
            plane.log,
            db_b,
            convert_sequences,
            consumer_name="scheduler",
            start_positions=db_b.positions("scheduler"),
        ).run_until_caught_up()

        # ONLY the suffix past the fence replayed -- count asserted exactly
        expected_suffix = _log_messages_from(plane.log, snapshot["fence"])
        assert replayed == expected_suffix
        assert 0 < replayed < total

        # queue definitions + executor heartbeats arrive out-of-band in this
        # harness (the test QueueRepository is not event-sourced; executors
        # re-register on their first post-restart heartbeat in production):
        # copy the live rows into BOTH worlds identically.
        for row in plane.db.list_queues():
            import json as _json

            for db in (db_a, db_b):
                db.upsert_queue(
                    row["name"],
                    weight=row["weight"],
                    cordoned=bool(row["cordoned"]),
                    owners=_json.loads(row["owners"]),
                    groups=_json.loads(row["groups_json"]),
                    labels=_json.loads(row["labels_json"]),
                )
        for row in plane.db.executors():
            for db in (db_a, db_b):
                db.upsert_executor(
                    row["executor_id"],
                    row["snapshot"],
                    row["last_updated_ns"],
                )

        # bit-equal materialized JobDb state...
        state_a = _canon_jobs(db_a, config)
        state_b = _canon_jobs(db_b, config)
        assert state_a == state_b
        assert len(state_a) > 10  # the churn actually built a world

        # ...and bit-equal next-cycle decisions
        now_s = plane.clock()
        assert _decisions_of(db_a, config, now_s) == _decisions_of(
            db_b, config, now_s
        )
        db_a.close()
        db_b.close()
    finally:
        plane.close()


def test_promotion_crash_drill_is_idempotent(tmp_path, monkeypatch):
    """leader_promote crash site: a cycle that dies mid-promotion (after
    winning the election, before the recovery fence completes) rewinds
    cleanly; the NEXT cycle re-runs the whole promotion and the plane
    serves -- and the publisher carries the held epoch forward."""
    from armada_tpu.core import faults
    from armada_tpu.server.submit import JobSubmitItem

    plane = ControlPlane.build(tmp_path)
    try:
        plane.server.create_queue(QueueRecord("promo"))
        plane.server.submit_jobs(
            "promo", "js", [JobSubmitItem(resources={"cpu": "1", "memory": "1"})]
        )
        plane.ingest()
        faults.reset_counters()
        monkeypatch.setenv("ARMADA_FAULT", "leader_promote:error")
        with pytest.raises(faults.FaultInjected):
            plane.scheduler.cycle(schedule=False)
        monkeypatch.delenv("ARMADA_FAULT")
        # the aborted promotion left no partial state: the retry promotes
        # and the world schedules end to end
        plane.run_until(
            lambda: "leased" in plane.job_states().values()
            or "succeeded" in plane.job_states().values(),
            max_steps=30,
        )
        # the scheduler stamped its election epoch on the publisher
        assert plane.publisher._epoch == 0  # standalone: generation 0
    finally:
        plane.close()


@pytest.mark.slow
def test_serve_restore_from_checkpoint_after_store_loss(tmp_path):
    """The full `serve` restart path: run a plane, checkpoint, kill it,
    WIPE the scheduler store (the cliff checkpoints exist for), restart --
    the new plane restores the snapshot, replays only the suffix, reports
    the durability block, and keeps serving."""
    import json as _json
    import urllib.request

    from armada_tpu.cli.serve import start_control_plane
    from armada_tpu.rpc.client import ArmadaClient
    from armada_tpu.server.submit import JobSubmitItem

    data = str(tmp_path / "data")
    cfg = SchedulingConfig(shape_bucket=32)
    p1 = start_control_plane(
        data, port=0, config=cfg, cycle_interval_s=0.05,
        schedule_interval_s=0.1,
    )
    try:
        c = ArmadaClient(f"127.0.0.1:{p1.port}")
        c.create_queue(QueueRecord("dur"))
        ids1 = c.submit_jobs(
            "dur", "js", [JobSubmitItem(resources={"cpu": "1", "memory": "1"})]
        )
        # wait until ingested, then checkpoint THROUGH the operator RPC
        import time as _time

        deadline = _time.time() + 20
        while (
            not p1._db.fetch_job_updates(0, 0)[0] and _time.time() < deadline
        ):
            _time.sleep(0.05)
        info = c.trigger_checkpoint()
        assert info["path"].endswith(".snap")
        # more events AFTER the fence: the suffix the restart must replay
        ids2 = c.submit_jobs(
            "dur", "js", [JobSubmitItem(resources={"cpu": "1", "memory": "1"})]
        )
        deadline = _time.time() + 20
        while (
            len(p1._db.fetch_job_updates(0, 0)[0]) < 2
            and _time.time() < deadline
        ):
            _time.sleep(0.05)
        c.close()
    finally:
        p1.stop()
    os.remove(os.path.join(data, "scheduler.db"))

    p2 = start_control_plane(
        data, port=0, config=cfg, cycle_interval_s=0.05,
        schedule_interval_s=0.1, health_port=0,
    )
    try:
        assert p2.restore_info["restored"]
        jobs, _ = p2._db.fetch_job_updates(0, 0)
        assert {r["job_id"] for r in jobs} == set(ids1 + ids2)
        # durability block rides /healthz
        body = _json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{p2.health_server.port}/healthz", timeout=5
            ).read()
        )
        assert body["durability"]["checkpoint"]["snapshot"]["path"].endswith(
            ".snap"
        )
        assert body["durability"]["epoch"] == 0
        # and the restarted plane still serves writes
        c2 = ArmadaClient(f"127.0.0.1:{p2.port}")
        assert c2.submit_jobs(
            "dur", "js2", [JobSubmitItem(resources={"cpu": "1", "memory": "1"})]
        )
        c2.close()
    finally:
        p2.stop()
