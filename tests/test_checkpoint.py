"""Durable checkpoints + statefile + epoch fencing: the unit tier.

The failure ladder under test ("never to wrong state", ISSUE 7): writes are
atomic + checksummed, a corrupt newest snapshot falls back to the previous
one, no snapshot falls back to full replay, and restore never moves a live
store backward.  Plus the publisher's epoch fence: a deposed leader's
publish is rejected the moment the election record carries a higher
generation.  The integration tier (restart bit-equality, promotion drill,
serve restore) lives in tests/test_restart_recovery.py.
"""

from __future__ import annotations

import os

import pytest

pytestmark = pytest.mark.fast

from armada_tpu.core import statefile
from armada_tpu.core.statefile import CorruptStateFile
from armada_tpu.events import events_pb2 as pb
from armada_tpu.ingest.schedulerdb import SchedulerDb
from armada_tpu.ingest.converter import convert_sequences
from armada_tpu.scheduler.checkpoint import (
    CheckpointManager,
    maybe_restore,
    restore_plane,
    snapshot_plane,
)


def _seq(job_id: str, queue: str = "q") -> pb.EventSequence:
    return pb.EventSequence(
        queue=queue,
        jobset="js",
        events=[
            pb.Event(
                created_ns=1,
                submit_job=pb.SubmitJob(
                    job_id=job_id, spec=pb.JobSpec(priority=0)
                ),
            )
        ],
    )


def _store(db: SchedulerDb, job_ids, positions) -> None:
    db.store(convert_sequences([_seq(j) for j in job_ids]),
             consumer="scheduler", next_positions=positions)


# --- statefile ---------------------------------------------------------------


def test_statefile_blob_roundtrip_and_corruption(tmp_path):
    path = str(tmp_path / "state.bin")
    statefile.write_blob(path, b"payload-bytes", version=3)
    assert statefile.read_blob(path) == (3, b"payload-bytes")
    # no stray tmp file left behind
    assert not os.path.exists(path + ".tmp")

    # truncation (torn write) fails loudly
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data[:-4])
    with pytest.raises(CorruptStateFile):
        statefile.read_blob(path)

    # bit rot fails the checksum
    with open(path, "wb") as f:
        f.write(data[:-1] + bytes([data[-1] ^ 0xFF]))
    with pytest.raises(CorruptStateFile):
        statefile.read_blob(path)

    # wrong magic (some other file dropped in place)
    with open(path, "wb") as f:
        f.write(b"not a state file at all")
    with pytest.raises(CorruptStateFile):
        statefile.read_blob(path)

    # absent stays distinguishable from corrupt
    with pytest.raises(FileNotFoundError):
        statefile.read_blob(str(tmp_path / "missing.bin"))


def test_statefile_json_roundtrip(tmp_path):
    path = str(tmp_path / "record.json")
    statefile.write_json(path, {"holder": "a", "generation": 3})
    # stays PLAIN json (existing readers like the lease file's json.load)
    import json

    with open(path) as f:
        assert json.load(f)["generation"] == 3
    assert statefile.read_json(path)["holder"] == "a"
    with open(path, "w") as f:
        f.write("{torn")
    with pytest.raises(CorruptStateFile):
        statefile.read_json(path)


# --- CheckpointManager -------------------------------------------------------


def test_manager_write_prune_and_newest(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2)
    db = SchedulerDb(":memory:")
    paths = []
    for i in range(3):
        _store(db, [f"j{i}"], {0: (i + 1) * 10})
        paths.append(mgr.write(snapshot_plane(db)))
    # pruned to keep=2, newest wins
    assert len(mgr.paths()) == 2
    payload, path = mgr.load_newest()
    assert path == paths[-1]
    assert payload["fence"] == {0: 30}
    assert len(payload["db"]["jobs"]) == 3
    status = mgr.status()
    assert status["snapshot"]["fence"] == {0: 30}
    assert status["snapshot"]["jobs"] == 3
    assert status["count"] == 2
    db.close()


def test_manager_falls_back_past_corrupt_newest(tmp_path):
    """The ladder: corrupt newest -> previous snapshot -> (none) full
    replay.  Corruption is reported, never raised."""
    mgr = CheckpointManager(str(tmp_path), keep=3)
    db = SchedulerDb(":memory:")
    _store(db, ["j1"], {0: 10})
    good = mgr.write(snapshot_plane(db))
    _store(db, ["j2"], {0: 20})
    bad = mgr.write(snapshot_plane(db))
    # tear the newest snapshot mid-file
    with open(bad, "rb") as f:
        data = f.read()
    with open(bad, "wb") as f:
        f.write(data[: len(data) // 2])
    payload, path = mgr.load_newest()
    assert path == good
    assert payload["fence"] == {0: 10}
    assert [p for p, _reason in mgr.skipped] == [bad]
    # both corrupt -> no usable snapshot, caller does full replay
    with open(good, "wb") as f:
        f.write(b"\x00" * 10)
    assert mgr.load_newest() is None
    assert len(mgr.skipped) == 2
    db.close()


def test_restore_policy_fast_forward_only(tmp_path):
    """maybe_restore: fresh store restores, store behind the fence
    restores, store AT/PAST the fence is never regressed."""
    mgr = CheckpointManager(str(tmp_path))
    src = SchedulerDb(":memory:")
    _store(src, ["j1", "j2"], {0: 100})
    mgr.write(snapshot_plane(src))

    fresh = SchedulerDb(":memory:")
    info = maybe_restore(fresh, mgr)
    assert info["restored"]
    assert {r["job_id"] for r in fresh.fetch_job_updates(0, 0)[0]} == {
        "j1",
        "j2",
    }
    assert fresh.positions("scheduler") == {0: 100}
    fresh.close()

    behind = SchedulerDb(":memory:")
    _store(behind, ["j1"], {0: 50})
    assert maybe_restore(behind, mgr)["restored"]
    assert behind.positions("scheduler") == {0: 100}
    behind.close()

    ahead = SchedulerDb(":memory:")
    _store(ahead, ["j1", "j2", "j3"], {0: 150})
    info = maybe_restore(ahead, mgr)
    assert not info["restored"]
    assert "at/past" in info["reason"]
    # the newer state survived untouched
    assert len(ahead.fetch_job_updates(0, 0)[0]) == 3
    assert ahead.positions("scheduler") == {0: 150}
    ahead.close()
    src.close()


def test_restore_is_transactional_against_midway_failure(tmp_path):
    """A failure mid-restore rolls back to the pre-restore state -- never a
    half-loaded store."""
    mgr = CheckpointManager(str(tmp_path))
    src = SchedulerDb(":memory:")
    _store(src, ["j1"], {0: 10})
    payload = snapshot_plane(src)
    # poison one table's rows so the bulk insert fails after earlier
    # tables already applied
    payload["db"]["queues"] = [("only-one-column",)]
    dst = SchedulerDb(":memory:")
    _store(dst, ["keep-me"], {0: 5})
    with pytest.raises(Exception):
        restore_plane(payload, dst)
    jobs, _ = dst.fetch_job_updates(0, 0)
    assert [r["job_id"] for r in jobs] == ["keep-me"]
    assert dst.positions("scheduler") == {0: 5}
    src.close()
    dst.close()


def test_snapshot_write_fault_leaves_previous_snapshot_usable(tmp_path):
    """The snapshot_write crash drill: an injected death before the write
    leaves recovery on the previous snapshot; the periodic trigger survives
    and retries."""
    from armada_tpu.core import faults

    mgr = CheckpointManager(str(tmp_path))
    db = SchedulerDb(":memory:")
    _store(db, ["j1"], {0: 10})
    first = mgr.write(snapshot_plane(db))
    faults.reset_counters()
    os.environ["ARMADA_FAULT"] = "snapshot_write:error"
    try:
        with pytest.raises(faults.FaultInjected):
            mgr.write(snapshot_plane(db))
    finally:
        os.environ.pop("ARMADA_FAULT", None)
    payload, path = mgr.load_newest()
    assert path == first
    # next attempt (fault is one-shot) succeeds and becomes newest
    second = mgr.write(snapshot_plane(db))
    assert mgr.load_newest()[1] == second
    db.close()


def test_scheduler_periodic_checkpoint_survives_write_failure(tmp_path):
    """Scheduler._maybe_checkpoint: a failing disk logs and retries at the
    interval cadence -- it must never take the loop down."""
    from armada_tpu.core import faults
    from armada_tpu.ingest.schedulerdb import SchedulerDb as Db
    from armada_tpu.jobdb.jobdb import JobDb
    from armada_tpu.scheduler import Scheduler, StandaloneLeaderController
    from armada_tpu.eventlog import EventLog
    from armada_tpu.eventlog.publisher import Publisher

    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    db = Db(":memory:")
    sched = Scheduler(
        db,
        JobDb(),
        algo=None,  # never cycles in this test
        publisher=Publisher(log),
        leader=StandaloneLeaderController(),
    )
    sched.checkpointer = CheckpointManager(str(tmp_path / "ckpt"))
    sched.checkpoint_interval_s = 0.0001
    faults.reset_counters()
    os.environ["ARMADA_FAULT"] = "snapshot_write:error"
    try:
        sched._maybe_checkpoint(leader=True)  # swallows the injected death
    finally:
        os.environ.pop("ARMADA_FAULT", None)
    assert sched.last_checkpoint is None
    import time as _time

    _time.sleep(0.001)
    sched._maybe_checkpoint(leader=True)
    assert sched.last_checkpoint is not None
    assert sched.checkpointer.load_newest() is not None
    # follower planes never snapshot (two replicas on shared storage
    # would race)
    sched.last_checkpoint = None
    sched._last_checkpoint_mono = 0.0
    sched._maybe_checkpoint(leader=False)
    assert sched.last_checkpoint is None
    db.close()
    log.close()


# --- epoch fence -------------------------------------------------------------


def test_epoch_fence_rejects_deposed_publisher(tmp_path):
    """Leader A (generation 1) is deposed by B (generation 2): A's
    publisher -- stamped with the epoch it last led at -- is rejected by
    the fence on the append choke point, B's serves.  Markers fence too."""
    from armada_tpu.eventlog import EventLog
    from armada_tpu.eventlog.publisher import DeposedEpoch, Publisher
    from armada_tpu.scheduler.leader import FileLeaseLeaderController

    clock = [100.0]
    lease = str(tmp_path / "leader.lease")
    a = FileLeaseLeaderController(
        lease, "a", lease_duration_s=10.0, clock=lambda: clock[0]
    )
    b = FileLeaseLeaderController(
        lease, "b", lease_duration_s=10.0, clock=lambda: clock[0]
    )
    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    pub_a = Publisher(log)
    pub_a.epoch_source = a.current_generation
    pub_b = Publisher(log)
    pub_b.epoch_source = b.current_generation

    tok_a = a.get_token()
    assert tok_a.leader
    pub_a.set_epoch(tok_a.generation)
    pub_a.publish([_seq("j1")])  # leading: accepted

    clock[0] += 11.0  # lease expires; B wins the next election
    tok_b = b.get_token()
    assert tok_b.leader and tok_b.generation > tok_a.generation
    pub_b.set_epoch(tok_b.generation)

    with pytest.raises(DeposedEpoch):
        pub_a.publish([_seq("j2")])
    with pytest.raises(DeposedEpoch):
        pub_a.publish_markers()
    pub_b.publish([_seq("j3")])  # the promoted leader serves

    # the deposed record's identity is in the error (forensics)
    try:
        pub_a.publish([_seq("j4")])
    except DeposedEpoch as e:
        assert e.held == tok_a.generation and e.current == tok_b.generation
    # A re-wins later: stamping the new generation re-admits it
    clock[0] += 11.0
    tok_a2 = a.get_token()
    assert tok_a2.leader
    pub_a.set_epoch(tok_a2.generation)
    pub_a.publish([_seq("j5")])
    log.close()


def test_standalone_controller_has_no_epochs():
    from armada_tpu.scheduler.leader import StandaloneLeaderController

    assert StandaloneLeaderController().current_generation() == 0
