"""The multi-language client codegen pipeline, exercised in CI.

docs/clients.md publishes the recipe; tools/genclients.sh is the runnable
form.  These tests regenerate the Java / C# / Kotlin message bindings from
the two wire protos on every run, then check the generated surface contains
what the thin clients (client/java, client/dotnet) compile against -- so a
proto change that breaks a binding language fails here, not at a user's
desk.  (Reference parity: client/DotNet, client/java, client/scala ship
generated bindings; the JVM/.NET toolchains to COMPILE them are not in this
image, so compilation is the user-side step documented in each build file.)
"""

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent

# genclients.sh drives the real protoc (the _minigen fallback only emits
# python); without the binary the pipeline itself cannot run
pytestmark = pytest.mark.skipif(
    shutil.which("protoc") is None, reason="protoc not on PATH"
)


@pytest.fixture(scope="module")
def generated(tmp_path_factory):
    out = tmp_path_factory.mktemp("genclients")
    res = subprocess.run(
        ["sh", str(ROOT / "tools" / "genclients.sh"), str(out),
         "java", "csharp", "kotlin"],
        capture_output=True,
        text=True,
    )
    assert res.returncode == 0, res.stderr
    return out


def test_java_messages_cover_the_client_surface(generated):
    rpc = generated / "java" / "armada_tpu" / "api" / "Rpc.java"
    events = generated / "java" / "armada_tpu" / "events" / "Events.java"
    assert rpc.is_file() and events.is_file()
    src = rpc.read_text()
    # every message ArmadaClient.java builds must exist as a nested class
    for cls in (
        "SubmitJobsRequest", "SubmitJobsResponse", "CancelJobsRequest",
        "PreemptJobsRequest", "ReprioritizeJobsRequest", "Queue",
        "QueueListResponse", "JobSetEventsRequest", "JobSetEventMessage",
        "LeaseJobRunsRequest",
    ):
        assert f"class {cls}" in src, f"Rpc.java lost message {cls}"
    assert "class EventSequence" in events.read_text()


def test_csharp_messages_cover_the_client_surface(generated):
    rpc = generated / "csharp" / "Rpc.cs"
    assert rpc.is_file() and (generated / "csharp" / "Events.cs").is_file()
    src = rpc.read_text()
    assert "namespace ArmadaTpu.Api" in src
    for cls in (
        "SubmitJobsRequest", "SubmitItem", "CancelJobsRequest",
        "JobSetEventMessage", "QueueListResponse",
    ):
        assert f"class {cls}" in src, f"Rpc.cs lost message {cls}"


def test_kotlin_bindings_generate(generated):
    kts = list((generated / "kotlin").rglob("*.kt"))
    assert kts, "kotlin codegen produced nothing"
    assert any("SubmitJobsRequestKt" in p.name for p in kts)


def test_thin_clients_reference_only_generated_messages(generated):
    """The hand-written wrappers must only name messages the generator
    actually emits (guards against drift between protos and clients)."""
    import re

    rpc_src = (generated / "java" / "armada_tpu" / "api" / "Rpc.java").read_text()
    java = (ROOT / "client/java/src/main/java/io/armadatpu/ArmadaClient.java").read_text()
    java_refs = set(re.findall(r"Rpc\.(\w+)\.newBuilder", java))
    java_refs |= set(re.findall(r"Rpc\.(\w+)\.getDefaultInstance", java))
    java_refs |= set(re.findall(r"Rpc\.(\w+)[>\s,)]", java))
    for m in sorted(java_refs):
        assert re.search(rf"class {m}\b", rpc_src), (
            f"ArmadaClient.java references Rpc.{m} which codegen does not emit"
        )
    cs_src = (generated / "csharp" / "Rpc.cs").read_text()
    cs = (ROOT / "client/dotnet/ArmadaClient.cs").read_text()
    generated_cs = {
        m for m in re.findall(r"sealed partial class (\w+)", cs_src)
    }
    # every generated-message type the thin client names, in any position:
    # generics, news, Parser references
    cs_refs = set(re.findall(r"new (\w+)(?:Request)?\s*[({]", cs))
    cs_refs |= set(re.findall(r"(\w+)\.Parser\.ParseFrom", cs))
    cs_refs |= set(re.findall(r"[<,]\s*(\w+)\s*[>,]", cs))
    suspects = {
        r for r in cs_refs
        if r.endswith(("Request", "Response", "Message", "Item", "Query"))
        or r in ("Queue", "Empty")
    }
    for m in sorted(suspects):
        assert m in generated_cs, (
            f"ArmadaClient.cs references {m} which codegen does not emit"
        )


def test_scala_client_references_only_generated_messages(generated):
    """The Scala thin client compiles against the SAME protoc-java messages
    as client/java (no ScalaPB): every Rpc.X it names must exist in the
    generated Java surface, and its gRPC method names must match the
    services the server actually registers (reference parity:
    client/scala/armada-scala-client)."""
    import re

    rpc_src = (generated / "java" / "armada_tpu" / "api" / "Rpc.java").read_text()
    scala = (
        ROOT / "client/scala/src/main/scala/io/armadatpu/ArmadaClient.scala"
    ).read_text()
    refs = set(re.findall(r"Rpc\.(\w+)", scala))
    for m in sorted(refs):
        assert re.search(rf"class {m}\b", rpc_src), (
            f"ArmadaClient.scala references Rpc.{m} which codegen does not emit"
        )
    # the verb surface matches the Java thin client (shared service set)
    java = (
        ROOT / "client/java/src/main/java/io/armadatpu/ArmadaClient.java"
    ).read_text()
    scala_methods = set(re.findall(r'"(armada_tpu\.api\.[\w./]+)"', scala))
    java_methods = set(re.findall(r'"(armada_tpu\.api\.[\w./]+)"', java))
    assert scala_methods, "Scala client names no gRPC methods"
    assert scala_methods >= java_methods, (
        f"Scala client missing verbs: {java_methods - scala_methods}"
    )
