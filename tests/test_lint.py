"""armada-lint: rule fixtures + the self-hosting gate.

Every registered rule is pinned by a fixture file under
tests/lint_fixtures/ holding exactly one true positive (the line marked
``# TP``) and at least one near miss the rule must NOT flag -- so a rule
that rots (starts missing its target, or starts flooding) fails here, not
in review.  The self-host test IS the CI gate: the whole tree must lint
clean, which wires tools/lint.py into the tier-1/fast command path.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from armada_tpu.analysis import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")

# rule -> (fixture file, synthetic relpath the buffer is linted under --
# rule scoping is path-based, fixtures opt into the scope they target)
RULE_FIXTURES = {
    "axis1-scatter": ("axis1_scatter.py", "armada_tpu/models/fixture.py"),
    "full-argmin": ("full_argmin.py", "armada_tpu/models/fair_scheduler.py"),
    "f64-score": ("f64_score.py", "armada_tpu/models/fair_scheduler.py"),
    "fetch-not-barrier": ("fetch_not_barrier.py", "armada_tpu/fixture.py"),
    "searchsorted-dtype": ("searchsorted_dtype.py", "fixture.py"),
    "fixed-sleep-retry": ("fixed_sleep_retry.py", "fixture.py"),
    "bare-except": ("bare_except.py", "fixture.py"),
    "wallclock-event-order": (
        "wallclock_event_order.py",
        "armada_tpu/eventlog/fixture.py",
    ),
    "slo-wallclock": ("slo_wallclock.py", "armada_tpu/loadgen/fixture.py"),
    "grpc-options": ("grpc_options.py", "armada_tpu/fixture.py"),
    "thread-no-daemon": ("thread_no_daemon.py", "armada_tpu/fixture.py"),
    "lock-held-sleep": ("lock_held_sleep.py", "fixture.py"),
    "mutable-default-arg": ("mutable_default_arg.py", "fixture.py"),
    "cursor-outside-txn": ("cursor_outside_txn.py", "armada_tpu/fixture.py"),
    "queued-version-write": (
        "queued_version_write.py",
        "armada_tpu/fixture.py",
    ),
    "atomic-state-file": (
        "atomic_state_file.py",
        "armada_tpu/fixture.py",
    ),
    "mesh-gather": ("mesh_gather.py", "armada_tpu/scheduler/fixture.py"),
    # dataflow-backed rules (armada-lint v2): each TP has a syntactic twin
    # in the same fixture -- see test_dataflow_rules_beat_syntax below
    "gathered-row-compute": (
        "gathered_row_compute.py",
        "armada_tpu/models/fixture.py",
    ),
    "branch-return-array": (
        "branch_return_array.py",
        "armada_tpu/models/fixture.py",
    ),
    "inloop-scatter-gathered-key": (
        "inloop_scatter_gathered_key.py",
        "armada_tpu/models/fixture.py",
    ),
    "commit-scatter-gathered-old": (
        "commit_scatter_gathered_old.py",
        "armada_tpu/models/fixture.py",
    ),
    "unpinned-out-shardings": (
        "unpinned_out_shardings.py",
        "armada_tpu/parallel/fixture.py",
    ),
    "unmade-lock": ("unmade_lock.py", "armada_tpu/ingest/fixture.py"),
    "pool-dispatch-mutation": (
        "pool_dispatch_mutation.py",
        "armada_tpu/scheduler/fixture.py",
    ),
    "shard-foreign-cursor": (
        "shard_foreign_cursor.py",
        "armada_tpu/ingest/fixture.py",
    ),
    "store-shard-foreign-write": (
        "store_shard_foreign_write.py",
        "armada_tpu/ingest/fixture.py",
    ),
    "dlq-cursor-same-txn": (
        "dlq_cursor_same_txn.py",
        "armada_tpu/ingest/fixture.py",
    ),
    # interprocedural rules (armada-lint v3): REDUCED-tag and helper-read
    # provenance from the dataflow engine
    "vectorized-accumulator-ordering": (
        "vectorized_accumulator_ordering.py",
        "armada_tpu/models/fixture.py",
    ),
    "class-signature-home": (
        "class_signature_home.py",
        "armada_tpu/scheduler/fixture.py",
    ),
}

# The value-flow rules whose fixtures carry a `# twin` line: a
# statement with the SAME normalized AST as the TP that must stay clean.
TWIN_RULES = [
    "gathered-row-compute",
    "branch-return-array",
    "inloop-scatter-gathered-key",
    "commit-scatter-gathered-old",
    "unpinned-out-shardings",
    "pool-dispatch-mutation",
    "shard-foreign-cursor",
    "store-shard-foreign-write",
    "dlq-cursor-same-txn",
    "vectorized-accumulator-ordering",
    "class-signature-home",
]

# armada-lint v3: the interprocedural costumes of the value-flow rules --
# provenance crossing a helper-function or nested-scope boundary that the
# v2 single-function def-use could not follow.  Same TP/twin discipline,
# separate fixtures so the v2 shapes stay pinned independently.
HELPER_BOUNDARY_FIXTURES = {
    "pool-dispatch-mutation": (
        "pool_dispatch_window.py",
        "armada_tpu/scheduler/fixture.py",
    ),
    "shard-foreign-cursor": (
        "shard_foreign_cursor_helper.py",
        "armada_tpu/ingest/fixture.py",
    ),
    "store-shard-foreign-write": (
        "store_shard_foreign_write_helper.py",
        "armada_tpu/ingest/fixture.py",
    ),
    "dlq-cursor-same-txn": (
        "dlq_cursor_same_txn_helper.py",
        "armada_tpu/ingest/fixture.py",
    ),
}


def test_registry_has_at_least_22_rules_all_pinned():
    names = lint.rule_names()
    assert len(names) >= 22
    assert len(names) == len(set(names))
    # every registered rule has a fixture, every fixture a registered rule
    assert set(RULE_FIXTURES) == set(names)


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_rule_true_positive_and_near_miss(rule):
    fname, relpath = RULE_FIXTURES[rule]
    path = os.path.join(FIXTURES, fname)
    with open(path) as fh:
        text = fh.read()
    tp_lines = [
        i for i, line in enumerate(text.splitlines(), 1) if "# TP" in line
    ]
    assert len(tp_lines) == 1, f"{fname} must mark exactly one '# TP' line"
    findings = lint.lint_source(text, relpath)
    assert [
        (f.rule, f.line) for f in findings
    ] == [(rule, tp_lines[0])], (
        f"{fname}: expected exactly the marked TP, got "
        + "; ".join(f.format() for f in findings)
    )


def _normalized_stmt(tree: "object", lineno: int) -> str:
    """The statement starting at `lineno`, with every Name identifier and
    Constant value scrubbed -- two statements with equal normalized dumps
    are indistinguishable to any per-node (syntactic) matcher."""
    import ast

    target = None
    for node in ast.walk(tree):
        if isinstance(node, ast.stmt) and node.lineno == lineno:
            target = node
            break
    assert target is not None, f"no statement at line {lineno}"
    import copy

    clone = copy.deepcopy(target)
    for node in ast.walk(clone):
        if isinstance(node, ast.Name):
            node.id = "_"
        elif isinstance(node, ast.Constant):
            node.value = 0
    return ast.dump(clone, annotate_fields=False, include_attributes=False)


@pytest.mark.parametrize("rule", TWIN_RULES)
def test_dataflow_rules_beat_syntax(rule):
    """The v2 claim, asserted by construction: the TP and its twin have
    IDENTICAL normalized ASTs (so no node-shape rule -- the whole v1
    engine -- could separate them), yet only the TP is flagged."""
    import ast

    fname, relpath = RULE_FIXTURES[rule]
    with open(os.path.join(FIXTURES, fname)) as fh:
        text = fh.read()
    lines = text.splitlines()
    tp = [i for i, l in enumerate(lines, 1) if "# TP" in l]
    twin = [i for i, l in enumerate(lines, 1) if "# twin" in l]
    assert len(tp) == 1 and len(twin) == 1, fname
    tree = ast.parse(text)
    assert _normalized_stmt(tree, tp[0]) == _normalized_stmt(tree, twin[0]), (
        f"{fname}: TP and twin must be syntactically identical after "
        "normalization -- otherwise a per-node matcher could separate them"
    )
    findings = lint.lint_source(text, relpath)
    assert [(f.rule, f.line) for f in findings] == [(rule, tp[0])]


@pytest.mark.parametrize("rule", sorted(HELPER_BOUNDARY_FIXTURES))
def test_interprocedural_fixtures_beat_syntax(rule):
    """The v3 claim: provenance survives project-helper hops (wrapped
    polls, row-builder delegation, rendered-plan transforms) and the
    windowed dispatch_pool_rounds container flow -- and the helper-hop TP
    still has a syntactically IDENTICAL twin that stays clean, so the
    separation is pure interprocedural value flow."""
    import ast

    fname, relpath = HELPER_BOUNDARY_FIXTURES[rule]
    with open(os.path.join(FIXTURES, fname)) as fh:
        text = fh.read()
    lines = text.splitlines()
    tp = [i for i, l in enumerate(lines, 1) if "# TP" in l]
    twin = [i for i, l in enumerate(lines, 1) if "# twin" in l]
    assert len(tp) == 1 and len(twin) == 1, fname
    tree = ast.parse(text)
    assert _normalized_stmt(tree, tp[0]) == _normalized_stmt(tree, twin[0]), (
        f"{fname}: TP and twin must be syntactically identical after "
        "normalization"
    )
    findings = lint.lint_source(text, relpath)
    assert [(f.rule, f.line) for f in findings] == [
        (rule, tp[0])
    ], "; ".join(f.format() for f in findings)


def test_unmade_lock_is_module_contextual():
    """unmade-lock's twin is the MODULE, not a line: the identical Lock
    statement goes clean once the module spawns no threads -- context no
    per-node matcher sees."""
    fname, relpath = RULE_FIXTURES["unmade-lock"]
    with open(os.path.join(FIXTURES, fname)) as fh:
        text = fh.read()
    assert lint.lint_source(text, relpath), "sanity: TP fires with threads"
    threadless = "\n".join(
        l for l in text.splitlines() if "spawn-marker" not in l
    )
    assert "threading.Lock()" in threadless
    assert lint.lint_source(threadless, relpath) == []


def test_slo_wallclock_scope_covers_trace_module():
    """Round-12 scope extension: ops/trace.py (the cycle-trace recorder)
    is inside slo-wallclock's scope -- its own TP + near-miss fixture pair
    pins the rule fires there and only on the marked line."""
    path = os.path.join(FIXTURES, "slo_wallclock_trace.py")
    with open(path) as fh:
        text = fh.read()
    tp_lines = [
        i for i, line in enumerate(text.splitlines(), 1) if "# TP" in line
    ]
    assert len(tp_lines) == 1
    findings = lint.lint_source(text, "armada_tpu/ops/trace.py")
    assert [(f.rule, f.line) for f in findings] == [
        ("slo-wallclock", tp_lines[0])
    ], "; ".join(f.format() for f in findings)
    # ... and the SAME buffer under a path outside the scope stays clean
    assert lint.lint_source(text, "armada_tpu/ops/other.py") == []


def test_gathered_row_compute_covers_type_tables():
    """Round-20 ledger row: the per-type throughput bias must be folded
    into type_bias rows at BUILD time (core/keys.type_score_tables) and
    only gathered in the while-loop body -- scaling the GATHERED bias row
    in-loop is the classic hoisting defeat in its heterogeneity costume,
    and the rule must catch it while the carry-scaled twin stays clean."""
    import ast

    path = os.path.join(FIXTURES, "type_table_gather.py")
    with open(path) as fh:
        text = fh.read()
    lines = text.splitlines()
    tp = [i for i, l in enumerate(lines, 1) if "# TP" in l]
    twin = [i for i, l in enumerate(lines, 1) if "# twin" in l]
    assert len(tp) == 1 and len(twin) == 1
    tree = ast.parse(text)
    assert _normalized_stmt(tree, tp[0]) == _normalized_stmt(tree, twin[0])
    findings = lint.lint_source(text, "armada_tpu/models/fixture.py")
    assert [(f.rule, f.line) for f in findings] == [
        ("gathered-row-compute", tp[0])
    ], "; ".join(f.format() for f in findings)


def test_selfhost_whole_tree_clean():
    """The CI gate: zero unsuppressed violations over the repo.  The
    <=30s budget is asserted in test_cli_json_mode on a FRESH interpreter
    (the CLI's real shape): this in-process walk inside a jax-loaded
    pytest heap measures allocator/GC pressure, not the engine -- the
    identical walk read 18s standalone and 48s CPU late in the fast tier
    (round 22), so an in-process timing assert here only detects how
    bloated the test session is."""
    n, findings = lint.lint_tree(REPO)
    assert n > 150  # the walk really covered the tree
    assert not findings, "\n".join(f.format() for f in findings)


def test_suppression_requires_reason():
    src = "import time\nx = 1  # lint: allow(bare-except)\n"
    findings = lint.lint_source(src, "fixture.py")
    assert [f.rule for f in findings] == ["allow-missing-reason"]


def test_suppression_same_line_and_comment_block_above():
    tp = "try:\n    pass\nexcept:  # lint: allow(bare-except) -- fixture\n    pass\n"
    assert lint.lint_source(tp, "fixture.py") == []
    block = (
        "try:\n    pass\n"
        "# lint: allow(bare-except) -- a multi-line\n"
        "# comment block directly above the flagged line\n"
        "except:\n    pass\n"
    )
    assert lint.lint_source(block, "fixture.py") == []
    # ... but an allow above INTERVENING CODE does not reach the except
    leaky = (
        "# lint: allow(bare-except) -- too far away\n"
        "try:\n    pass\nexcept:\n    pass\n"
    )
    assert [f.rule for f in lint.lint_source(leaky, "fixture.py")] == [
        "bare-except"
    ]


def test_suppression_on_any_line_of_a_multiline_statement():
    """The allow may trail ANY line the flagged statement spans -- the
    Finding carries the statement's full span, not just its first line."""
    src = (
        "import threading\n"
        "t = threading.Thread(\n"
        "    target=print,\n"
        ")  # lint: allow(thread-no-daemon) -- fixture: closing-line allow\n"
    )
    assert lint.lint_source(src, "armada_tpu/fixture.py") == []


def test_suppression_multiple_rules_one_allow():
    src = (
        "import threading\n"
        "# lint: allow(thread-no-daemon, mutable-default-arg) -- fixture\n"
        "def f(x=[]):\n"
        "    return threading.Thread(target=f)\n"
    )
    # the allow covers the def line; the Thread call sits on the next line
    # and still needs its own -- pin that suppression is LINE-scoped
    findings = lint.lint_source(src, "armada_tpu/fixture.py")
    assert [f.rule for f in findings] == ["thread-no-daemon"]


def test_suppression_does_not_leak_to_other_rules():
    src = "try:\n    pass\nexcept:  # lint: allow(full-argmin) -- wrong rule\n    pass\n"
    findings = lint.lint_source(src, "fixture.py")
    assert [f.rule for f in findings] == ["bare-except"]


def test_syntax_error_is_a_finding_not_a_crash():
    findings = lint.lint_source("def broken(:\n", "fixture.py")
    assert [f.rule for f in findings] == ["syntax-error"]


def test_fixture_dir_is_excluded_from_the_walk():
    for path in lint.iter_python_files(REPO):
        assert "lint_fixtures" not in path


def test_cli_json_mode_within_budget():
    """ONE JSON line, clean tree -- and the documented <=30s full-tree
    budget (docs/lint.md), measured on the fresh interpreter every real
    CLI invocation gets (the v3 engine reads ~18s serial on the 1-CPU
    round-22 host; an in-process measurement late in the fast tier is
    inflated ~2.7x by the session heap and asserts nothing about the
    engine)."""
    import time

    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), "--json"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    elapsed = time.monotonic() - t0
    assert out.returncode == 0, out.stdout + out.stderr
    lines = [l for l in out.stdout.splitlines() if l.strip()]
    assert len(lines) == 1  # ONE JSON line (the bench.py discipline)
    doc = json.loads(lines[0])
    assert doc["ok"] is True and doc["violations"] == 0
    assert doc["rules"] >= 12 and doc["files"] > 150
    assert elapsed < 30.0, f"full-tree CLI walk took {elapsed:.1f}s (budget 30s)"


def test_cli_diff_mode_restricts_the_walk():
    """--diff lints only files changed vs a ref (+ untracked): the scope
    is a subset of the full walk and a clean tree still exits 0."""
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "lint.py"),
            "--diff",
            "--json",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout.strip())
    assert doc["ok"] is True
    full = sum(1 for _ in lint.iter_python_files(REPO))
    assert 0 <= doc["files"] <= full


def test_cli_stats_census():
    """--stats prints the suppression census: every reasoned allow shows
    up under its rule so stale exemptions stay visible."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), "--stats"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    # the kernel's blocked-minima allows are permanent census residents
    assert "full-argmin" in out.stdout
    assert "fair_scheduler.py" in out.stdout
    rows = lint.suppression_census(REPO)
    assert rows and all(reason for _, _, _, reason in rows)
    # every censused allow names a REGISTERED rule -- an allow referencing
    # a renamed/deleted rule is a stale exemption nothing enforces (the
    # round-19 store-shard rule rename hazard: an allow for a rule that no
    # longer exists suppresses nothing and rots silently)
    names = set(lint.rule_names())
    stale = [
        (p, ln, r)
        for p, ln, r, _ in rows
        if r not in names
        # the engine's own docstring demonstrates the allow syntax with
        # placeholder rule names; everything else must name a real rule
        and p != "armada_tpu/analysis/lint.py"
    ]
    assert not stale, f"allows for unregistered rules: {stale}"


def test_cli_jobs_parallel_matches_serial():
    """--jobs N fans per-file analysis over processes; the result set is
    the same (the self-host gate stays meaningful under parallelism)."""
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "lint.py"),
            "--jobs",
            "2",
            "--json",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout.strip())
    assert doc["ok"] is True and doc["violations"] == 0
    assert doc["files"] > 150


def test_cli_cache_cold_then_warm_clean():
    """--cache: the cold run populates .lint-cache.json and the warm run
    serves every entry from recorded file+dep hashes -- same file count,
    still clean, and fast enough that the replay clearly skipped the
    analyses.  Combined with --jobs to pin the deps-returning worker path."""
    import time

    cache = os.path.join(REPO, ".lint-cache.json")
    if os.path.exists(cache):
        os.remove(cache)
    tool = os.path.join(REPO, "tools", "lint.py")
    try:
        cold = subprocess.run(
            [sys.executable, tool, "--cache", "--jobs", "4", "--json"],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=120,
        )
        assert cold.returncode == 0, cold.stdout + cold.stderr
        doc = json.loads(cold.stdout.strip())
        assert doc["ok"] is True and doc["files"] > 150
        assert os.path.exists(cache)
        t0 = time.monotonic()
        warm = subprocess.run(
            [sys.executable, tool, "--cache", "--json"],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=120,
        )
        warm_s = time.monotonic() - t0
        assert warm.returncode == 0, warm.stdout + warm.stderr
        wdoc = json.loads(warm.stdout.strip())
        assert wdoc["ok"] is True and wdoc["violations"] == 0
        assert wdoc["files"] == doc["files"]
        # hash replay, not re-analysis: the serial cold walk is ~18s on
        # this host; a warm walk is interpreter startup + 233 hashes
        assert warm_s < 10.0, f"warm --cache run took {warm_s:.1f}s"
    finally:
        if os.path.exists(cache):
            os.remove(cache)


def test_cache_invalidates_on_dep_edit(tmp_path):
    """A cached entry is keyed by the linted file AND its dataflow deps:
    editing a helper MODULE re-lints the dependent without touching it.
    Pinned end to end through lint_file_deps' recorded hash map."""
    helper = tmp_path / "helper_mod.py"
    helper.write_text("def make_row(rec):\n    return [rec]\n")
    user = tmp_path / "user_mod.py"
    user.write_text("import helper_mod\n\n\nx = helper_mod.make_row(1)\n")
    findings, deps = lint.lint_file_deps(str(user), str(tmp_path))
    assert findings == []
    assert "user_mod.py" in deps
    # the dep map hashes the file itself; a content edit changes its key
    from armada_tpu.analysis import dataflow as _df

    old = deps["user_mod.py"]
    user.write_text("import helper_mod\n\n\nx = helper_mod.make_row(2)\n")
    assert _df.content_hash(str(user)) != old


def test_cli_flags_violations_nonzero(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), str(bad)],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert out.returncode == 1
    assert "bare-except" in out.stdout
