"""Configurable pending-pod checks (internal/executor/podchecks/
pod_checks.go + config.yaml pendingPodChecks)."""

import pytest

from armada_tpu.executor.podchecks import (
    ACTION_FAIL,
    ACTION_RETRY,
    PodCheckRule,
    evaluate,
    rules_from_config,
)
from tests.control_plane import ControlPlane
from armada_tpu.server import JobSubmitItem, QueueRecord


def test_rule_matching_grace_and_inverse():
    fail_fast = PodCheckRule(regexp="InvalidImageName", action=ACTION_FAIL)
    backoff = PodCheckRule(
        regexp="ImagePullBackOff", action=ACTION_RETRY, grace_s=60
    )
    progress = PodCheckRule(
        regexp="nodes are available", action=ACTION_RETRY, grace_s=120, inverse=True
    )
    rules = (fail_fast, backoff, progress)
    # fail-fast matches immediately
    assert evaluate(rules, "InvalidImageName: https://x", 0) == ACTION_FAIL
    # backoff respects its grace period
    assert evaluate(rules, "ImagePullBackOff", 30) is None
    assert evaluate(rules, "ImagePullBackOff", 90) == ACTION_RETRY
    # inverse: no scheduling progress at all -> retry after the grace
    assert evaluate(rules, "", 60) is None
    assert evaluate(rules, "", 150) == ACTION_RETRY
    assert evaluate(rules, "0/3 nodes are available", 150) is None


def test_rules_from_reference_shaped_yaml():
    rules = rules_from_config(
        [
            {"regexp": "Failed to pull image", "action": "Fail", "gracePeriod": "90s"},
            {"regexp": "nodes are available", "action": "Retry",
             "gracePeriod": "5m", "inverse": True},
        ]
    )
    assert rules[0].action == ACTION_FAIL and rules[0].grace_s == 90.0
    assert rules[1].inverse and rules[1].grace_s == 300.0
    with pytest.raises(ValueError, match="action"):
        PodCheckRule(regexp="x", action="explode")


def test_fail_fast_rule_fails_job_terminally(tmp_path):
    cp = ControlPlane.build(tmp_path, executor_specs={"ex1": (2, "8", "32")})
    cp.server.create_queue(QueueRecord("q"))
    ex = cp.executors[0]
    ex._pod_check_rules = (
        PodCheckRule(regexp="InvalidImageName", action=ACTION_FAIL),
    )
    ex.cluster._start_delay = 10_000.0  # stays PENDING
    (jid,) = cp.server.submit_jobs(
        "q", "js", [JobSubmitItem(resources={"cpu": "2", "memory": "2"})]
    )
    ex.run_once()
    cp.ingest()
    cp.scheduler.cycle()
    cp.ingest()
    ex.run_once()
    (pod,) = ex.cluster.pod_states()
    ex.cluster.set_pod_message(pod.run_id, "InvalidImageName: https://oops")
    assert ex.check_stuck_pods() == 1
    assert ex.cluster.pod_states() == []
    cp.ingest()
    res = cp.scheduler.cycle()
    assert res.events_by_kind().get("job_errors") == 1  # terminal, no requeue
    assert cp.jobdb.read_txn().get(jid) is None or cp.jobdb.read_txn().get(jid).failed
    cp.close()


def test_retry_rule_returns_lease_and_reschedules(tmp_path):
    cp = ControlPlane.build(tmp_path, executor_specs={"ex1": (2, "8", "32")})
    cp.server.create_queue(QueueRecord("q"))
    ex = cp.executors[0]
    ex._pod_check_rules = (
        PodCheckRule(regexp="ImagePullBackOff", action=ACTION_RETRY, grace_s=30),
    )
    ex.cluster._start_delay = 10_000.0
    (jid,) = cp.server.submit_jobs(
        "q", "js", [JobSubmitItem(resources={"cpu": "2", "memory": "2"})]
    )
    ex.run_once()
    cp.ingest()
    cp.scheduler.cycle()
    cp.ingest()
    ex.run_once()
    (pod,) = ex.cluster.pod_states()
    ex.cluster.set_pod_message(pod.run_id, "ImagePullBackOff")
    assert ex.check_stuck_pods() == 0  # inside the grace period
    cp.clock.advance(60.0)
    ex.cluster.tick(0.0)
    assert ex.check_stuck_pods() == 1
    cp.ingest()
    res = cp.scheduler.cycle()
    assert res.events_by_kind().get("job_requeued") == 1
    cp.close()


def test_fail_beats_retry_regardless_of_order():
    """maxAction semantics (podchecks/action.go): a retryable symptom never
    masks a fatal one in the same diagnostics."""
    rules = (
        PodCheckRule(regexp="Back-off pulling image", action=ACTION_RETRY),
        PodCheckRule(regexp="InvalidImageName", action=ACTION_FAIL),
    )
    both = "Back-off pulling image x; InvalidImageName: bad"
    assert evaluate(rules, both, 10) == ACTION_FAIL


def test_k8s_adapter_surfaces_scheduling_conditions():
    from tests.fake_kube_api import FakeKubeApi
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.core.types import JobSpec
    from armada_tpu.executor.kubernetes import KubernetesClusterContext

    F = SchedulingConfig(shape_bucket=32).resource_list_factory()
    kube = FakeKubeApi()
    try:
        ctx = KubernetesClusterContext(kube.url, F)
        ctx.submit_pod(
            "r1", "j1", "q", "js",
            JobSpec(id="j1", queue="q",
                    resources=F.from_mapping({"cpu": "1", "memory": "1"})),
            "w1",
        )
        ((ns, name),) = kube.pods
        kube.pods[(ns, name)]["status"] = {
            "phase": "Pending",
            "conditions": [
                {"type": "PodScheduled", "status": "False",
                 "reason": "Unschedulable",
                 "message": "0/3 nodes are available: insufficient cpu"}
            ],
        }
        (p,) = ctx.pod_states()
        assert "0/3 nodes are available" in p.message
        # an inverse no-progress rule correctly sees progress text
        rule = PodCheckRule(regexp="nodes are available", action=ACTION_RETRY,
                            grace_s=0, inverse=True)
        assert evaluate((rule,), p.message, 100) is None
    finally:
        kube.stop()


def test_retryable_failed_pod_requeues_instead_of_failing(tmp_path):
    """failedpodchecks: a FAILED pod matching a retryable regex returns the
    lease (job reschedules); non-matching failures stay terminal."""
    from armada_tpu.executor.podchecks import FailedPodRetryChecker

    cp = ControlPlane.build(tmp_path, executor_specs={"ex1": (2, "8", "32")})
    cp.server.create_queue(QueueRecord("q"))
    ex = cp.executors[0]
    ex._failed_pod_checker = FailedPodRetryChecker(("node shutdown", "Evicted"))

    def run_to_failure(message):
        (jid,) = cp.server.submit_jobs(
            "q", "js", [JobSubmitItem(resources={"cpu": "2", "memory": "2"})]
        )
        ex.run_once()
        cp.ingest()
        cp.scheduler.cycle()
        cp.ingest()
        ex.run_once()
        run = cp.jobdb.read_txn().get(jid).latest_run
        ex.cluster.fail_pod(run.id, message)
        ex.report_cycle()
        ex.cleanup()
        cp.ingest()
        return cp.scheduler.cycle()

    res1 = run_to_failure("node shutdown during maintenance")
    assert res1.events_by_kind().get("job_requeued") == 1

    res2 = run_to_failure("OOMKilled: exit 137")
    assert res2.events_by_kind().get("job_errors") == 1
    cp.close()


def test_checks_from_config_mapping_and_list():
    from armada_tpu.executor.podchecks import checks_from_config

    pend, failed = checks_from_config(
        {
            "pending": [{"regexp": "ImagePullBackOff", "action": "Retry"}],
            "failedRetryable": ["node shutdown"],
        }
    )
    assert len(pend) == 1 and failed.is_retryable("node shutdown now")
    assert not failed.is_retryable("OOMKilled")
    pend2, failed2 = checks_from_config([{"regexp": "x", "action": "Fail"}])
    assert len(pend2) == 1 and not failed2.is_retryable("anything")


def test_config_rejects_unknown_sections_and_bad_types():
    from armada_tpu.executor.podchecks import checks_from_config

    with pytest.raises(ValueError, match="unknown pod-check sections"):
        checks_from_config({"pendingPodChecks": []})
    with pytest.raises(ValueError, match="list or mapping"):
        checks_from_config("regexp: x")


def test_init_container_statuses_feed_diagnostics():
    from armada_tpu.executor.kubernetes import _pod_message

    msg = _pod_message(
        {
            "initContainerStatuses": [
                {"state": {"waiting": {"reason": "InvalidImageName"}}}
            ]
        }
    )
    assert "InvalidImageName" in msg
