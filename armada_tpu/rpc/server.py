"""The gRPC server: Submit + Event + ExecutorApi services on one port.

Equivalent of the reference's grpc server builder (internal/common/grpc/
grpc.go) wiring api.Submit / api.Event (internal/server/server.go:41) and
executorapi.ExecutorApi (internal/scheduler/schedulerapp.go).  Handlers are
registered with grpc generic handlers; each delegates 1:1 to the in-process
service objects, mapping domain errors to canonical status codes.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Optional

import grpc

from armada_tpu.rpc import convert, rpc_pb2 as pb
from armada_tpu.server.auth import AuthorizationError, Principal
from armada_tpu.server.authn import (
    AnonymousAuthenticator,
    AuthenticationError,
    MultiAuthenticator,
    TrustedHeaderAuthenticator,
)
from armada_tpu.server.queues import QueueAlreadyExists, QueueNotFound
from armada_tpu.server.submit import NotLeader, SubmitError


def default_authenticator() -> MultiAuthenticator:
    """Dev-mode chain (the reference's anonymousAuth default): trusted
    headers honoured, everything else anonymous.  Production deployments
    pass an explicit chain (server/authn.py authn_from_config) where
    trusted headers are an opt-in."""
    return MultiAuthenticator([TrustedHeaderAuthenticator(), AnonymousAuthenticator()])


def _authenticate(auth, context) -> Principal:
    """Resolve the caller or abort UNAUTHENTICATED.  Runs on EVERY service
    handler -- an unauthenticated or forged request never reaches a service."""
    meta = {k.lower(): v for k, v in (context.invocation_metadata() or ())}
    try:
        return auth.authenticate(meta)
    except AuthenticationError as e:
        context.abort(grpc.StatusCode.UNAUTHENTICATED, str(e))


def _trace_id_of(context) -> str:
    """The caller's cycle-trace id, when it chose to propagate one
    (ops/trace.py cross-process stitching).  Plain metadata, never trusted
    for anything but labelling."""
    for k, v in context.invocation_metadata() or ():
        if k.lower() == "x-armada-trace-id":
            return str(v)[:64]
    return ""


def _guard(context, fn):
    """Run fn(), translating domain errors to gRPC status codes."""
    try:
        return fn()
    except NotLeader as e:
        # retryable: the client re-resolves (k8s readiness keeps followers
        # out of the Service; direct clients follow the message's address)
        context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
    except SubmitError as e:
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
    except AuthorizationError as e:
        context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
    except QueueNotFound as e:
        context.abort(grpc.StatusCode.NOT_FOUND, f"queue {e} not found")
    except QueueAlreadyExists as e:
        context.abort(grpc.StatusCode.ALREADY_EXISTS, f"queue {e} exists")
    except ValueError as e:
        # e.g. queue weight validation in the repository
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))


class _SubmitService:
    def __init__(self, server, auth):
        self._server = server
        self._auth = auth

    def SubmitJobs(self, request, context):
        principal = _authenticate(self._auth, context)
        items = [convert.submit_item_from_proto(m) for m in request.items]
        ids = _guard(
            context,
            lambda: self._server.submit_jobs(
                request.queue, request.jobset, items, principal
            ),
        )
        return pb.SubmitJobsResponse(job_ids=ids)

    def CancelJobs(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._server.cancel_jobs(
                request.queue,
                request.jobset,
                list(request.job_ids),
                request.reason,
                principal,
            ),
        )
        return pb.Empty()

    def CancelJobSet(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._server.cancel_jobset(
                request.queue,
                request.jobset,
                list(request.states),
                request.reason,
                principal,
            ),
        )
        return pb.Empty()

    def PreemptJobs(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._server.preempt_jobs(
                request.queue,
                request.jobset,
                list(request.job_ids),
                request.reason,
                principal,
            ),
        )
        return pb.Empty()

    def ReprioritizeJobs(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._server.reprioritize_jobs(
                request.queue,
                request.jobset,
                int(request.priority),
                list(request.job_ids),
                principal,
            ),
        )
        return pb.Empty()

    def CreateQueue(self, request, context):
        principal = _authenticate(self._auth, context)
        record = convert.queue_from_proto(request)
        _guard(context, lambda: self._server.create_queue(record, principal))
        return pb.Empty()

    def UpdateQueue(self, request, context):
        principal = _authenticate(self._auth, context)
        record = convert.queue_from_proto(request)
        _guard(context, lambda: self._server.update_queue(record, principal))
        return pb.Empty()

    def DeleteQueue(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(context, lambda: self._server.delete_queue(request.name, principal))
        return pb.Empty()

    def GetQueue(self, request, context):
        _authenticate(self._auth, context)
        record = self._server.get_queue(request.name)
        if record is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"queue {request.name!r} not found")
        return convert.queue_to_proto(record)

    def ListQueues(self, request, context):
        _authenticate(self._auth, context)
        return pb.QueueListResponse(
            queues=[convert.queue_to_proto(q) for q in self._server.list_queues()]
        )


class _EventService:
    def __init__(self, event_api, auth):
        self._api = event_api
        self._auth = auth

    def GetJobSetEvents(self, request, context):
        _authenticate(self._auth, context)
        if not request.watch:
            # Page until a short read: jobsets can exceed one batch.
            idx = int(request.from_idx)
            while True:
                batch = self._api.get_jobset_events(request.queue, request.jobset, idx)
                for item in batch:
                    yield pb.JobSetEventMessage(idx=item.idx, sequence=item.sequence)
                if not batch:
                    return
                idx = batch[-1].idx + 1
        stop = threading.Event()
        context.add_callback(stop.set)
        idle = request.idle_timeout_s or None
        for item in self._api.watch(
            request.queue,
            request.jobset,
            from_idx=int(request.from_idx),
            stop=stop,
            idle_timeout_s=idle,
        ):
            yield pb.JobSetEventMessage(idx=item.idx, sequence=item.sequence)


class _LookoutService:
    """JSON-over-gRPC lookout queries (the reference's REST surface)."""

    def __init__(self, queries, auth):
        self._queries = queries
        self._auth = auth

    def GetJobs(self, request, context):
        _authenticate(self._auth, context)
        import json

        from armada_tpu.lookout.queries import JobFilter, JobOrder

        try:
            q = json.loads(request.query_json or "{}")
            filters = [JobFilter(**f) for f in q.get("filters", [])]
            order = JobOrder(**q["order"]) if q.get("order") else None
            jobs = self._queries.get_jobs(
                filters,
                order,
                skip=int(q.get("skip", 0)),
                take=int(q.get("take", 100)),
            )
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.JsonResponse(json=json.dumps(jobs))

    def GroupJobs(self, request, context):
        _authenticate(self._auth, context)
        import json

        from armada_tpu.lookout.queries import JobFilter

        try:
            q = json.loads(request.query_json or "{}")
            filters = [JobFilter(**f) for f in q.get("filters", [])]
            groups = self._queries.group_jobs(
                q.get("group_by", "state"),
                filters,
                aggregates=tuple(q.get("aggregates", ("state",))),
                take=int(q.get("take", 100)),
                annotation_key=q.get("annotation_key", ""),
            )
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.JsonResponse(json=json.dumps(groups))

    def GetJobDetails(self, request, context):
        _authenticate(self._auth, context)
        import json

        details = self._queries.get_job_details(request.name)
        if details is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"job {request.name!r} not found")
        return pb.JsonResponse(json=json.dumps(details))


class _ReportsService:
    """SchedulingReports (internal/scheduler/reports/server.go) as JSON.

    `reports` may be a plain SchedulingReportsRepository or the
    LeaderProxyingReports wrapper (leader_proxying_reports_server.go):
    followers then answer by forwarding to the leader, and a follower that
    cannot reach the leader aborts UNAVAILABLE (retryable), never a
    misleading NOT_FOUND."""

    def __init__(self, reports, auth):
        self._reports = reports
        self._auth = auth

    def _guard(self, context, fn):
        from armada_tpu.scheduler.reports import ReportsUnavailable

        try:
            return fn()
        except ReportsUnavailable as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    def GetJobReport(self, request, context):
        _authenticate(self._auth, context)
        import json

        report = self._guard(
            context, lambda: self._reports.job_report(request.name)
        )
        if report is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"no report for job {request.name!r}"
            )
        return pb.JsonResponse(json=json.dumps(report))

    def GetQueueReport(self, request, context):
        _authenticate(self._auth, context)
        import json

        return pb.JsonResponse(
            json=json.dumps(
                self._guard(
                    context, lambda: self._reports.queue_report(request.name)
                )
            )
        )

    def GetPoolReport(self, request, context):
        _authenticate(self._auth, context)
        import json

        return pb.JsonResponse(
            json=json.dumps(
                self._guard(
                    context,
                    lambda: self._reports.pool_report(request.name or None),
                )
            )
        )


class _BinocularsService:
    """Logs + Cordon next to the cluster (internal/binoculars)."""

    def __init__(self, binoculars, auth, authorizer=None):
        from armada_tpu.server.auth import ActionAuthorizer

        self._b = binoculars
        self._auth = auth
        self._authz = authorizer or ActionAuthorizer()

    def Logs(self, request, context):
        _authenticate(self._auth, context)
        try:
            text = self._b.logs(job_id=request.job_id, run_id=request.run_id)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return pb.LogsResponse(log=text)

    def Cordon(self, request, context):
        # Cordon is a privileged node mutation: the reference gates it on
        # the CordonNodes permission (cordon.go:48-51 -> PermissionDenied).
        from armada_tpu.server.auth import AuthorizationError, Permission

        principal = _authenticate(self._auth, context)
        try:
            self._authz.authorize_action(principal, Permission.CORDON_NODES)
        except AuthorizationError as e:
            context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
        try:
            self._b.cordon(
                request.node_id,
                cordoned=not request.uncordon,
                user=principal.name,
            )
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return pb.Empty()


class _ExecutorAdminService:
    """Operator actions on executors/queues (pkg/api/executor.proto): each
    verb publishes a control-plane event (server/controlplane.py)."""

    def __init__(self, control_plane, auth):
        self._cp = control_plane
        self._auth = auth

    def UpsertExecutorSettings(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._cp.upsert_executor_settings(
                request.name,
                cordoned=request.cordoned,
                cordon_reason=request.cordon_reason,
                principal=principal,
            ),
        )
        return pb.Empty()

    def DeleteExecutorSettings(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._cp.delete_executor_settings(
                request.name, principal=principal
            ),
        )
        return pb.Empty()

    def PreemptOnExecutor(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._cp.preempt_on_executor(
                request.name,
                queues=list(request.queues),
                priority_classes=list(request.priority_classes),
                principal=principal,
            ),
        )
        return pb.Empty()

    def CancelOnExecutor(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._cp.cancel_on_executor(
                request.name,
                queues=list(request.queues),
                priority_classes=list(request.priority_classes),
                principal=principal,
            ),
        )
        return pb.Empty()

    def TriggerCheckpoint(self, request, context):
        principal = _authenticate(self._auth, context)
        info = _guard(
            context, lambda: self._cp.trigger_checkpoint(principal)
        )
        return pb.CheckpointTriggerResponse(
            path=info.get("path", ""),
            created_ns=int(info.get("created_ns", 0)),
            epoch=int(info.get("epoch", 0)),
            fenced_offset_total=sum(info.get("fence", {}).values()),
        )

    def CheckpointStatus(self, request, context):
        import json as _json

        principal = _authenticate(self._auth, context)
        status = _guard(
            context, lambda: self._cp.get_checkpoint_status(principal)
        )
        return pb.CheckpointStatusResponse(status_json=_json.dumps(status))

    def DumpTrace(self, request, context):
        import json as _json

        principal = _authenticate(self._auth, context)
        doc = _guard(context, lambda: self._cp.dump_trace(principal))
        return pb.JsonResponse(json=_json.dumps(doc))

    def QuarantineStatus(self, request, context):
        import json as _json

        principal = _authenticate(self._auth, context)
        block = _guard(
            context, lambda: self._cp.quarantine_status(principal)
        )
        return pb.JsonResponse(json=_json.dumps(block))

    def QuarantineClear(self, request, context):
        import json as _json

        principal = _authenticate(self._auth, context)
        out = _guard(
            context,
            lambda: self._cp.quarantine_clear(request.name, principal),
        )
        return pb.JsonResponse(json=_json.dumps(out))

    # Dead-letter verbs (ingest/dlq.py): the selector rides
    # QueueGetRequest.name ('consumer[:partition[:offset]]'), the JSON
    # document rides JsonResponse -- no proto changes, same shape as the
    # quarantine verbs.
    def DlqStatus(self, request, context):
        import json as _json

        principal = _authenticate(self._auth, context)
        out = _guard(context, lambda: self._cp.dlq_status(principal))
        return pb.JsonResponse(json=_json.dumps(out))

    def DlqList(self, request, context):
        import json as _json

        principal = _authenticate(self._auth, context)
        out = _guard(
            context, lambda: self._cp.dlq_list(request.name, principal)
        )
        return pb.JsonResponse(json=_json.dumps(out))

    def DlqShow(self, request, context):
        import json as _json

        principal = _authenticate(self._auth, context)
        out = _guard(
            context, lambda: self._cp.dlq_show(request.name, principal)
        )
        return pb.JsonResponse(json=_json.dumps(out))

    def DlqReplay(self, request, context):
        import json as _json

        principal = _authenticate(self._auth, context)
        out = _guard(
            context, lambda: self._cp.dlq_replay(request.name, principal)
        )
        return pb.JsonResponse(json=_json.dumps(out))

    def DlqDiscard(self, request, context):
        import json as _json

        principal = _authenticate(self._auth, context)
        out = _guard(
            context, lambda: self._cp.dlq_discard(request.name, principal)
        )
        return pb.JsonResponse(json=_json.dumps(out))

    def PreemptOnQueue(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._cp.preempt_on_queue(
                request.name,
                priority_classes=list(request.priority_classes),
                principal=principal,
            ),
        )
        return pb.Empty()

    def CancelOnQueue(self, request, context):
        principal = _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._cp.cancel_on_queue(
                request.name,
                priority_classes=list(request.priority_classes),
                job_states=list(request.job_states),
                principal=principal,
            ),
        )
        return pb.Empty()


class _LogReplicationService:
    """Stream the local durable log to follower replicas
    (eventlog/replicator.py LogReplicator) -- cross-host HA without a
    shared volume."""

    def __init__(self, eventlog, auth, poll_interval_s: float = 0.05):
        self._log = eventlog
        self._auth = auth
        self._poll = poll_interval_s

    def GetLogInfo(self, request, context):
        _authenticate(self._auth, context)
        return pb.LogInfoResponse(
            num_partitions=self._log.num_partitions,
            end_offsets=[
                self._log.end_offset(p)
                for p in range(self._log.num_partitions)
            ],
        )

    def TailLog(self, request, context):
        _authenticate(self._auth, context)
        partition = int(request.partition)
        if not 0 <= partition < self._log.num_partitions:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"partition {partition} out of range",
            )
        offset = int(request.from_offset)
        idle = float(request.idle_timeout_s) or 5.0
        stop = threading.Event()
        context.add_callback(stop.set)
        deadline = time.monotonic() + idle
        while not stop.is_set():
            batch = self._log.read(partition, offset)
            if batch:
                deadline = time.monotonic() + idle
                for m in batch:
                    yield pb.LogRecord(
                        partition=partition,
                        offset=m.offset,
                        key=m.key,
                        payload=m.payload,
                    )
                offset = batch[-1].next_offset
                continue
            if not request.follow:
                return
            if time.monotonic() > deadline:
                return  # idle: follower reconnects (re-resolving the leader)
            stop.wait(self._poll)


class _ScheduleService:
    """The scheduling sidecar (scheduler/sidecar.py): the TPU round kernel
    behind the SchedulingAlgo boundary (scheduling_algo.go:36-41) for
    external control planes."""

    def __init__(self, sidecar, auth):
        self._sidecar = sidecar
        self._auth = auth

    def _session_guard(self, context, fn):
        from armada_tpu.scheduler.sidecar import SessionExists, UnknownSession

        try:
            return fn()
        except UnknownSession as e:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"unknown session {e.args[0]!r}"
            )
        except SessionExists as e:
            context.abort(
                grpc.StatusCode.ALREADY_EXISTS,
                f"session {e.args[0]!r} already exists",
            )
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

    def CreateSession(self, request, context):
        _authenticate(self._auth, context)
        sid = self._session_guard(
            context,
            lambda: self._sidecar.create_session(
                request.session_id, request.config_yaml
            ),
        )
        return pb.ScheduleSessionHandle(session_id=sid)

    def SyncState(self, request, context):
        _authenticate(self._auth, context)
        tid = _trace_id_of(context)
        self._session_guard(
            context, lambda: self._sidecar.handle_sync(request, trace_id=tid)
        )
        return pb.Empty()

    def ScheduleRound(self, request, context):
        _authenticate(self._auth, context)
        tid = _trace_id_of(context)
        return self._session_guard(
            context, lambda: self._sidecar.handle_round(request, trace_id=tid)
        )

    def CloseSession(self, request, context):
        _authenticate(self._auth, context)
        self._sidecar.close_session(request.session_id)
        return pb.Empty()


class _ExecutorApiService:
    def __init__(self, executor_api, factory, auth):
        self._api = executor_api
        self._factory = factory
        self._auth = auth

    def LeaseJobRuns(self, request, context):
        _authenticate(self._auth, context)
        req = convert.lease_request_from_proto(request, self._factory)
        return convert.lease_response_to_proto(self._api.lease_job_runs(req))

    def ReportEvents(self, request, context):
        _authenticate(self._auth, context)
        _guard(
            context,
            lambda: self._api.report_events(list(request.sequences)),
        )
        return pb.Empty()


def _unary(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def _server_stream(fn, req_cls):
    return grpc.unary_stream_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def server_options(
    max_message_mb: Optional[int] = None,
    keepalive_time_s: Optional[float] = None,
    keepalive_timeout_s: float = 20.0,
) -> list:
    """Channel options for a hardened server (VERDICT #6): the shared
    message-cap/keepalive set (rpc/transport.py -- clients build theirs
    from the same module so the caps agree) plus server-side ping
    enforcement (accept client pings at >=5s spacing)."""
    from armada_tpu.rpc.transport import channel_options

    return channel_options(
        max_message_mb=max_message_mb,
        keepalive_time_s=keepalive_time_s,
        keepalive_timeout_s=keepalive_timeout_s,
    ) + [
        ("grpc.http2.min_recv_ping_interval_without_data_ms", 5000),
    ]


def make_server(
    submit_server=None,
    event_api=None,
    executor_api=None,
    factory=None,
    lookout_queries=None,
    reports=None,
    binoculars=None,
    binoculars_authorizer=None,
    control_plane=None,
    schedule_sidecar=None,
    replication_log=None,
    address: str = "127.0.0.1:0",
    max_workers: int = 16,
    authenticator=None,
    max_message_mb: Optional[int] = None,
    keepalive_time_s: Optional[float] = None,
) -> tuple[grpc.Server, int]:
    """Build and start a server hosting whichever services are given;
    returns (server, bound_port).  `authenticator` gates EVERY handler;
    None = the dev chain (trusted headers + anonymous).  Transport
    hardening (message caps, keepalive) comes from `server_options`;
    graceful drain is the caller's `server.stop(grace_s)` -- armadactl
    serve wires it to SIGTERM."""
    auth = authenticator if authenticator is not None else default_authenticator()
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=server_options(
            max_message_mb=max_message_mb, keepalive_time_s=keepalive_time_s
        ),
    )
    handlers = []
    if submit_server is not None:
        svc = _SubmitService(submit_server, auth)
        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.Submit",
                {
                    "SubmitJobs": _unary(svc.SubmitJobs, pb.SubmitJobsRequest),
                    "CancelJobs": _unary(svc.CancelJobs, pb.CancelJobsRequest),
                    "CancelJobSet": _unary(svc.CancelJobSet, pb.CancelJobSetRequest),
                    "PreemptJobs": _unary(svc.PreemptJobs, pb.PreemptJobsRequest),
                    "ReprioritizeJobs": _unary(
                        svc.ReprioritizeJobs, pb.ReprioritizeJobsRequest
                    ),
                    "CreateQueue": _unary(svc.CreateQueue, pb.Queue),
                    "UpdateQueue": _unary(svc.UpdateQueue, pb.Queue),
                    "DeleteQueue": _unary(svc.DeleteQueue, pb.QueueGetRequest),
                    "GetQueue": _unary(svc.GetQueue, pb.QueueGetRequest),
                    "ListQueues": _unary(svc.ListQueues, pb.Empty),
                },
            )
        )
    if event_api is not None:
        esvc = _EventService(event_api, auth)
        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.Event",
                {
                    "GetJobSetEvents": _server_stream(
                        esvc.GetJobSetEvents, pb.JobSetEventsRequest
                    ),
                },
            )
        )
    if lookout_queries is not None:
        lsvc = _LookoutService(lookout_queries, auth)
        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.Lookout",
                {
                    "GetJobs": _unary(lsvc.GetJobs, pb.LookoutQuery),
                    "GroupJobs": _unary(lsvc.GroupJobs, pb.LookoutQuery),
                    "GetJobDetails": _unary(lsvc.GetJobDetails, pb.QueueGetRequest),
                },
            )
        )
    if reports is not None:
        rsvc = _ReportsService(reports, auth)
        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.Reports",
                {
                    "GetJobReport": _unary(rsvc.GetJobReport, pb.QueueGetRequest),
                    "GetQueueReport": _unary(rsvc.GetQueueReport, pb.QueueGetRequest),
                    "GetPoolReport": _unary(rsvc.GetPoolReport, pb.QueueGetRequest),
                },
            )
        )
    if binoculars is not None:
        bsvc = _BinocularsService(binoculars, auth, binoculars_authorizer)
        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.Binoculars",
                {
                    "Logs": _unary(bsvc.Logs, pb.LogsRequest),
                    "Cordon": _unary(bsvc.Cordon, pb.CordonRequest),
                },
            )
        )
    if control_plane is not None:
        csvc = _ExecutorAdminService(control_plane, auth)
        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.ExecutorAdmin",
                {
                    "UpsertExecutorSettings": _unary(
                        csvc.UpsertExecutorSettings,
                        pb.ExecutorSettingsUpsertRequest,
                    ),
                    "DeleteExecutorSettings": _unary(
                        csvc.DeleteExecutorSettings,
                        pb.ExecutorSettingsDeleteRequest,
                    ),
                    "PreemptOnExecutor": _unary(
                        csvc.PreemptOnExecutor, pb.ExecutorScopedActionRequest
                    ),
                    "CancelOnExecutor": _unary(
                        csvc.CancelOnExecutor, pb.ExecutorScopedActionRequest
                    ),
                    "PreemptOnQueue": _unary(
                        csvc.PreemptOnQueue, pb.QueueScopedActionRequest
                    ),
                    "CancelOnQueue": _unary(
                        csvc.CancelOnQueue, pb.QueueScopedActionRequest
                    ),
                    "TriggerCheckpoint": _unary(
                        csvc.TriggerCheckpoint, pb.Empty
                    ),
                    "CheckpointStatus": _unary(
                        csvc.CheckpointStatus, pb.Empty
                    ),
                    "DumpTrace": _unary(csvc.DumpTrace, pb.Empty),
                    "QuarantineStatus": _unary(
                        csvc.QuarantineStatus, pb.Empty
                    ),
                    "QuarantineClear": _unary(
                        csvc.QuarantineClear, pb.QueueGetRequest
                    ),
                    "DlqStatus": _unary(csvc.DlqStatus, pb.Empty),
                    "DlqList": _unary(csvc.DlqList, pb.QueueGetRequest),
                    "DlqShow": _unary(csvc.DlqShow, pb.QueueGetRequest),
                    "DlqReplay": _unary(csvc.DlqReplay, pb.QueueGetRequest),
                    "DlqDiscard": _unary(
                        csvc.DlqDiscard, pb.QueueGetRequest
                    ),
                },
            )
        )
    if replication_log is not None:
        rlsvc = _LogReplicationService(replication_log, auth)
        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.LogReplication",
                {
                    "GetLogInfo": _unary(rlsvc.GetLogInfo, pb.LogInfoRequest),
                    "TailLog": _server_stream(
                        rlsvc.TailLog, pb.TailLogRequest
                    ),
                },
            )
        )
    if schedule_sidecar is not None:
        ssvc = _ScheduleService(schedule_sidecar, auth)
        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.Schedule",
                {
                    "CreateSession": _unary(
                        ssvc.CreateSession, pb.ScheduleSessionConfig
                    ),
                    "SyncState": _unary(ssvc.SyncState, pb.SyncStateRequest),
                    "ScheduleRound": _unary(
                        ssvc.ScheduleRound, pb.ScheduleRoundRequest
                    ),
                    "CloseSession": _unary(
                        ssvc.CloseSession, pb.ScheduleSessionHandle
                    ),
                },
            )
        )
    if executor_api is not None:
        if factory is None:
            raise ValueError("executor_api service requires a ResourceListFactory")
        xsvc = _ExecutorApiService(executor_api, factory, auth)
        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.ExecutorApi",
                {
                    "LeaseJobRuns": _unary(xsvc.LeaseJobRuns, pb.LeaseJobRunsRequest),
                    "ReportEvents": _unary(xsvc.ReportEvents, pb.ReportEventsRequest),
                },
            )
        )
    server.add_generic_rpc_handlers(tuple(handlers))
    port = server.add_insecure_port(address)
    server.start()
    return server, port
