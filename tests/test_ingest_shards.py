"""Partition-parallel ingestion (ingest/shards.py): the ISSUE 15 contract.

The parity pin: draining the SAME event log through the serial
IngestionPipeline and through PartitionedIngestionPipeline (any shard
count, inline or subprocess conversion, with or without a mid-drain
per-shard ingest_ack crash + restart) must materialize bit-equal scheduler
state -- raw serial columns excluded, as everywhere (batching differs, so
serial VALUES legitimately diverge; see tests/test_restart_recovery.py).
Plus the control-plane barrier (a queue sweep sees every event published
before it, across all partitions), the publisher wakeup hook, the bounded
stop() abandon discipline, and the log's partition-count adoption."""

from __future__ import annotations

import os
import threading
import time

import pytest

from armada_tpu.eventlog import EventLog, Publisher
from armada_tpu.events import events_pb2 as pb
from armada_tpu.ingest import (
    IngestionPipeline,
    PartitionedIngestionPipeline,
    SchedulerDb,
    convert_sequences,
)
from armada_tpu.ingest import shards as shards_mod
from armada_tpu.loadgen.workload import (
    CancelOp,
    MixConfig,
    ReprioritizeOp,
    SubmitOp,
    WorkloadGenerator,
)
from armada_tpu.server.queues import QueueRecord
from tests.control_plane import ControlPlane

SHARDS = 4


def _materialized(db: SchedulerDb) -> dict:
    """Every materialized table as canonical tuples, serial columns and the
    serials counter table scrubbed (the bit-equality surface)."""
    from armada_tpu.ingest.schedulerdb import SNAPSHOT_TABLES

    snap = db.export_snapshot()
    out = {}
    for table, cols in SNAPSHOT_TABLES.items():
        if table == "serials":
            continue
        rows = snap[table]
        if "serial" in cols:
            i = cols.index("serial")
            rows = [r[:i] + r[i + 1 :] for r in rows]
        out[table] = sorted(rows)
    return out


def _churn_plane(tmp_path, seed: int) -> ControlPlane:
    """A world with real submit/cancel/reprioritise/gang churn + scheduling
    cycles, so the log carries the full production event mix (leases, run
    transitions, requeues, errors)."""
    plane = ControlPlane.build(tmp_path)
    jobset = f"shards-{seed}"
    mix = MixConfig(
        num_queues=2,
        queue_prefix=f"sh{seed}",
        jobset=jobset,
        gang_fraction=0.15,
    )
    gen = WorkloadGenerator(mix, seed=seed)
    for q in gen.queues:
        plane.server.create_queue(QueueRecord(q))
    for _ in range(6):
        for op in gen.next_ops(10):
            if isinstance(op, SubmitOp):
                ids = plane.server.submit_jobs(op.queue, jobset, op.items)
                gen.note_submitted(op.queue, ids)
            elif isinstance(op, CancelOp):
                plane.server.cancel_jobs(
                    op.queue, jobset, op.job_ids, reason="churn"
                )
            elif isinstance(op, ReprioritizeOp):
                plane.server.reprioritize_jobs(
                    op.queue, jobset, op.priority, job_ids=op.job_ids
                )
        plane.step()
    plane.ingest()
    return plane


def _serial_replay(log) -> SchedulerDb:
    db = SchedulerDb(":memory:")
    IngestionPipeline(
        log, db, convert_sequences, consumer_name="scheduler"
    ).run_until_caught_up()
    return db


@pytest.mark.parametrize(
    "seed,mode", [(0, "process"), (1, "inline"), (2, "inline")]
)
def test_sharded_replay_bit_equal_serial_over_churn(
    tmp_path, monkeypatch, seed, mode
):
    """Serial vs sharded drains of the same churned log materialize
    identical state; seed 0 additionally routes conversion through the
    subprocess pool (the production sharded shape)."""
    monkeypatch.setenv("ARMADA_INGEST_SHARDS", str(SHARDS))
    monkeypatch.setenv("ARMADA_INGEST_CONVERT", "inline")
    plane = _churn_plane(tmp_path, seed)
    try:
        db_serial = _serial_replay(plane.log)
        db_sharded = SchedulerDb(":memory:")
        pipe = PartitionedIngestionPipeline(
            plane.log,
            db_sharded,
            convert_sequences,
            consumer_name="scheduler",
            num_shards=SHARDS,
            convert_mode=mode,
        )
        n = pipe.run_until_caught_up()
        assert n > 0
        assert _materialized(db_serial) == _materialized(db_sharded)
        assert db_serial.positions("scheduler") == db_sharded.positions(
            "scheduler"
        )
        db_serial.close()
        db_sharded.close()
    finally:
        plane.close()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_exactly_once_under_per_shard_crash(tmp_path, monkeypatch, seed):
    """The satellite drill: ingest_ack fires in ONE shard mid-drain (its
    batch COMMITTED, the in-memory ack died), the pipeline is restarted
    from the store's committed positions, and the final state is bit-equal
    to the serial drain -- under the tsan race harness."""
    from armada_tpu.analysis import tsan
    from armada_tpu.core import faults

    monkeypatch.setenv("ARMADA_INGEST_SHARDS", str(SHARDS))
    monkeypatch.setenv("ARMADA_INGEST_CONVERT", "inline")
    plane = _churn_plane(tmp_path, seed)
    tsan_was = tsan.enabled()
    monkeypatch.setenv("ARMADA_TSAN", "1")
    tsan.enable()
    tsan.reset()
    try:
        db_serial = _serial_replay(plane.log)
        db_sharded = SchedulerDb(":memory:")
        faults.reset_counters()
        # after_n=1: the crash lands mid-drain, past the first batch.
        monkeypatch.setenv("ARMADA_FAULT", "ingest_ack:error:1")
        pipe = PartitionedIngestionPipeline(
            plane.log,
            db_sharded,
            convert_sequences,
            consumer_name="scheduler",
            num_shards=SHARDS,
            convert_mode="inline",
        )
        with pytest.raises(faults.FaultInjected):
            pipe.run_until_caught_up()
        monkeypatch.delenv("ARMADA_FAULT")
        # The crashed shard's batch is committed but unacked: a RESTARTED
        # plane resumes from the store's positions and must not double-
        # apply it.
        pipe2 = PartitionedIngestionPipeline(
            plane.log,
            db_sharded,
            convert_sequences,
            consumer_name="scheduler",
            num_shards=SHARDS,
            start_positions=db_sharded.positions("scheduler"),
            convert_mode="inline",
        )
        pipe2.run_until_caught_up()
        assert _materialized(db_serial) == _materialized(db_sharded)
        violations = tsan.take_violations()
        assert not violations, "\n".join(violations)
        db_serial.close()
        db_sharded.close()
    finally:
        if not tsan_was:
            tsan.disable()
        plane.close()


def test_control_plane_jobset_constant_matches_server():
    """shards.py duplicates the reserved stream name by value (workers must
    not import the server package); this pins the two never diverge."""
    from armada_tpu.server.controlplane import CONTROL_PLANE_JOBSET

    assert shards_mod.CONTROL_PLANE_JOBSET == CONTROL_PLANE_JOBSET


def _submit_event(jid: str) -> pb.Event:
    return pb.Event(
        created_ns=1,
        submit_job=pb.SubmitJob(job_id=jid, spec=pb.JobSpec()),
    )


def test_control_plane_barrier_orders_sweep_after_all_partitions(tmp_path):
    """A CancelOnQueue sweep published AFTER submits spread over every
    partition must see all of them at apply time, even though the sweep's
    shard could otherwise race ahead of its siblings."""
    log = EventLog(str(tmp_path / "log"), num_partitions=8)
    pub = Publisher(log)
    pub.publish(
        [
            pb.EventSequence(
                queue="cq", jobset=f"js{i}", events=[_submit_event(f"cjob{i}")]
            )
            for i in range(64)
        ]
    )
    pub.publish(
        [
            pb.EventSequence(
                queue="",
                jobset=shards_mod.CONTROL_PLANE_JOBSET,
                events=[
                    pb.Event(
                        created_ns=5,
                        cancel_on_queue=pb.CancelOnQueue(name="cq"),
                    )
                ],
            )
        ]
    )
    db = SchedulerDb(":memory:")
    pipe = PartitionedIngestionPipeline(
        log,
        db,
        convert_sequences,
        consumer_name="scheduler",
        num_shards=4,
        convert_mode="inline",
    )
    pipe.run_until_caught_up()
    jobs, _ = db.fetch_job_updates(0, 0)
    assert len(jobs) == 64
    assert all(r["cancel_requested"] == 1 for r in jobs)
    db.close()
    log.close()


def test_control_plane_barrier_threaded(tmp_path):
    """Same guarantee with background shard threads: the barrier waits on
    sibling COMMITS instead of driving them inline."""
    log = EventLog(str(tmp_path / "log"), num_partitions=8)
    pub = Publisher(log)
    pub.publish(
        [
            pb.EventSequence(
                queue="tq", jobset=f"js{i}", events=[_submit_event(f"tjob{i}")]
            )
            for i in range(64)
        ]
    )
    pub.publish(
        [
            pb.EventSequence(
                queue="",
                jobset=shards_mod.CONTROL_PLANE_JOBSET,
                events=[
                    pb.Event(
                        created_ns=5,
                        cancel_on_queue=pb.CancelOnQueue(name="tq"),
                    )
                ],
            )
        ]
    )
    db = SchedulerDb(":memory:")
    pipe = PartitionedIngestionPipeline(
        log,
        db,
        convert_sequences,
        consumer_name="scheduler",
        num_shards=4,
        convert_mode="inline",
    )
    pub.add_wakeup(pipe.notify)
    pipe.start()
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            jobs, _ = db.fetch_job_updates(0, 0)
            if len(jobs) == 64 and all(
                r["cancel_requested"] == 1 for r in jobs
            ):
                break
            time.sleep(0.02)
        else:
            raise AssertionError("sweep did not converge under threads")
    finally:
        pipe.stop()
        db.close()
        log.close()


def test_threaded_barrier_resyncs_sibling_partition_cursor(tmp_path):
    """Regression: the fence drains the control shard's OTHER owned
    partitions past its prefetch read cursor; the loop must resync ALL
    owned partitions afterward or it re-reads (and re-applies) the drained
    span AFTER the sweep and commits that cursor backward.  Tiny poll
    batches force the multi-batch window the bug needs."""
    from armada_tpu.eventlog.publisher import jobset_key, partition_for_key

    log = EventLog(str(tmp_path / "log"), num_partitions=4)
    pub = Publisher(log)
    # Deterministic trigger: every submit lands on the control shard's
    # SIBLING partition (same shard, different partition -- chosen by key
    # hash), and the control record is ALONE on the control partition, so
    # the sweep is detected on the very first 2KB poll round while the
    # sibling still holds ~20 undrained batches -- exactly the window
    # where the fence drains past the prefetch cursor.
    control = shards_mod.control_partition_of(log)
    sibling = (control + 2) % 4
    seqs = []
    i = 0
    while len(seqs) < 400:
        jobset = f"js{i}"
        i += 1
        if partition_for_key(jobset_key("rq", jobset), 4) != sibling:
            continue
        seqs.append(
            pb.EventSequence(
                queue="rq",
                jobset=jobset,
                events=[_submit_event(f"rjob{len(seqs)}")],
            )
        )
    pub.publish(seqs)
    pub.publish(
        [
            pb.EventSequence(
                queue="",
                jobset=shards_mod.CONTROL_PLANE_JOBSET,
                events=[
                    pb.Event(
                        created_ns=5,
                        cancel_on_queue=pb.CancelOnQueue(name="rq"),
                    )
                ],
            )
        ]
    )
    db = SchedulerDb(":memory:")
    pipe = PartitionedIngestionPipeline(
        log,
        db,
        convert_sequences,
        consumer_name="scheduler",
        num_shards=2,  # control shard owns 2 partitions
        convert_mode="inline",
        max_bytes_per_partition=2048,
    )
    pipe.start()
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and sum(pipe.lag().values()):
            time.sleep(0.02)
        assert sum(pipe.lag().values()) == 0
    finally:
        pipe.stop()
    jobs, _ = db.fetch_job_updates(0, 0)
    assert len(jobs) == 400
    # the barrier guarantee: every submit published before the sweep is
    # swept (NOTE this is deliberately STRONGER than a one-poll serial
    # drain, where submits in partitions after the sweep's apply later)
    assert all(r["cancel_requested"] == 1 for r in jobs)
    # cursors ended exactly at the log end (never regressed); partitions
    # that never carried data never get cursor rows
    assert db.positions("scheduler") == {
        p: log.end_offset(p) for p in range(4) if log.end_offset(p)
    }
    # ... and nothing was re-read: 401 published sequences, 401 processed.
    # The pre-fix loop re-read the span the fence had drained past the
    # prefetch cursor and re-applied it after the sweep.
    assert pipe.total_sequences == 401
    db.close()
    log.close()


def test_wakeup_hook_beats_the_poll_interval(tmp_path):
    """With a deliberately huge poll interval, a publish still becomes
    visible promptly through the publisher wakeup hook -- the fixed idle
    poll is a fallback, not the latency floor."""
    log = EventLog(str(tmp_path / "log"), num_partitions=4)
    pub = Publisher(log)
    db = SchedulerDb(":memory:")
    pipe = PartitionedIngestionPipeline(
        log,
        db,
        convert_sequences,
        consumer_name="scheduler",
        num_shards=2,
        poll_interval=30.0,
        convert_mode="inline",
    )
    pub.add_wakeup(pipe.notify)
    pipe.start()
    try:
        time.sleep(0.2)  # let the shards reach their idle wait
        t0 = time.monotonic()
        pub.publish(
            [
                pb.EventSequence(
                    queue="wq", jobset="wjs", events=[_submit_event("wake-1")]
                )
            ]
        )
        while time.monotonic() - t0 < 5.0:
            jobs, _ = db.fetch_job_updates(0, 0)
            if jobs:
                break
            time.sleep(0.005)
        latency = time.monotonic() - t0
        assert jobs and jobs[0]["job_id"] == "wake-1"
        assert latency < 5.0  # far under the 30s poll interval
    finally:
        pipe.stop()
        db.close()
        log.close()


class _WedgedSink:
    """A sink whose store never returns (a dead database mid-call)."""

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()

    def store(self, batch, consumer="x", next_positions=None):
        self.entered.set()
        self.release.wait(30.0)

    def positions(self, consumer="x"):
        return {}


@pytest.mark.parametrize("cls", ["serial", "sharded"])
def test_stop_abandons_wedged_store_thread(tmp_path, cls):
    """The satellite fix: stop() joins with a timeout and ABANDONS a store
    wedged past it (the watchdog discipline) instead of hanging SIGTERM
    drain forever."""
    log = EventLog(str(tmp_path / "log"), num_partitions=2)
    Publisher(log).publish(
        [pb.EventSequence(queue="q", jobset="j", events=[_submit_event("w1")])]
    )
    sink = _WedgedSink()
    if cls == "serial":
        pipe = IngestionPipeline(log, sink, convert_sequences, "wedge")
    else:
        pipe = PartitionedIngestionPipeline(
            log,
            sink,
            convert_sequences,
            "wedge",
            num_shards=2,
            convert_mode="inline",
        )
    pipe.start()
    assert sink.entered.wait(10.0)
    t0 = time.monotonic()
    pipe.stop(timeout_s=0.3)
    assert time.monotonic() - t0 < 5.0  # did not wait for the wedged store
    assert not pipe.alive()
    assert pipe.snapshot()["abandoned_threads"] >= 1
    sink.release.set()  # drain the zombie so the test process stays clean
    log.close()


def test_eventlog_partition_adoption_and_mismatch(tmp_path):
    """num_partitions=None adopts the persisted width (the serve restart
    path); an explicit mismatch still refuses."""
    path = str(tmp_path / "log")
    log = EventLog(path, num_partitions=6)
    log.close()
    adopted = EventLog(path)  # no explicit count: adopt META
    assert adopted.num_partitions == 6
    adopted.close()
    with pytest.raises(ValueError, match="6 partitions"):
        EventLog(path, num_partitions=8)


def test_render_store_plan_matches_store(tmp_path):
    """render_scheduler_ops + store_plan == store for the full renderable
    op mix (the worker-side path is the same SQL by construction; this
    pins it stays that way)."""
    from armada_tpu.ingest.schedulerdb import render_scheduler_ops

    events = [
        _submit_event("p1"),
        pb.Event(job_validated=pb.JobValidated(job_id="p1", pools=["d"])),
        pb.Event(
            job_run_leased=pb.JobRunLeased(
                job_id="p1",
                run_id="r1",
                executor_id="e1",
                node_id="n1",
                pool="d",
                scheduled_at_priority=10,
                update_sequence_number=1,
            )
        ),
        pb.Event(job_run_running=pb.JobRunRunning(job_id="p1", run_id="r1")),
        pb.Event(
            job_run_errors=pb.JobRunErrors(
                job_id="p1",
                run_id="r1",
                errors=[pb.Error(reason="oom", message="x", terminal=True)],
            )
        ),
        pb.Event(job_succeeded=pb.JobSucceeded(job_id="p1")),
        pb.Event(
            queue_upsert=pb.QueueUpsert(name="qq", weight=2.0)
        ),
    ]
    ops_batch = convert_sequences(
        [pb.EventSequence(queue="q", jobset="js", events=events)]
    )
    plan = render_scheduler_ops(ops_batch)
    assert plan is not None
    db_a = SchedulerDb(":memory:")
    db_a.store(ops_batch, next_positions={0: 10})
    db_b = SchedulerDb(":memory:")
    db_b.store_plan(plan, next_positions={0: 10})
    assert _materialized(db_a) == _materialized(db_b)
    # ... and the columnar pipe packing round-trips the plan exactly
    unpacked = shards_mod._unpack_plan(shards_mod._pack_plan(plan))
    db_c = SchedulerDb(":memory:")
    db_c.store_plan(unpacked, next_positions={0: 10})
    assert _materialized(db_a) == _materialized(db_c)
    db_a.close()
    db_b.close()
    db_c.close()


def test_unrenderable_sweep_falls_back_to_ops(tmp_path):
    """A batch holding an apply-time-membership op (CancelOnQueue) renders
    to None -- the shard ships raw ops and the sink applies them
    in-transaction instead."""
    from armada_tpu.ingest import dbops
    from armada_tpu.ingest.schedulerdb import render_scheduler_ops

    batch = [
        dbops.InsertJobs(jobs={"z1": {"job_id": "z1", "queue": "q", "jobset": "j"}}),
        dbops.CancelOnQueue(queue="q"),
    ]
    assert render_scheduler_ops(batch) is None


def test_sharded_world_end_to_end(tmp_path, monkeypatch):
    """The whole control plane driven with sharded ingesters (the
    chaos_cycle --ingest-shards shape): jobs submit, lease and finish
    through PartitionedIngestionPipeline."""
    monkeypatch.setenv("ARMADA_INGEST_SHARDS", "2")
    monkeypatch.setenv("ARMADA_INGEST_CONVERT", "inline")
    plane = ControlPlane.build(tmp_path)
    try:
        assert isinstance(
            plane.scheduler_pipeline, PartitionedIngestionPipeline
        )
        from armada_tpu.server.submit import JobSubmitItem

        plane.server.create_queue(QueueRecord("swq"))
        plane.server.submit_jobs(
            "swq",
            "js",
            [JobSubmitItem(resources={"cpu": "1", "memory": "1"})],
        )
        plane.run_until(
            lambda: "succeeded" in plane.job_states().values(), max_steps=40
        )
    finally:
        plane.close()
