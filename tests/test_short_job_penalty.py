"""Short-job penalty: recently-exited short jobs keep charging their queue
(internal/scheduler/scheduling/short_job_penalty.go; scheduling_algo.go:342-360;
queue_scheduler.go:514-515 GetAllocationInclShortJobPenalty;
scheduler.go:436-447 JobDb retention)."""

import pytest

from armada_tpu.core.config import (
    PoolConfig,
    SchedulingConfig,
    parse_duration_s,
    scheduling_config_from_dict,
)
from armada_tpu.core.types import JobSpec, NodeSpec, Queue
from armada_tpu.jobdb.job import Job, JobRun
from armada_tpu.models import run_scheduling_round
from armada_tpu.scheduler.short_job_penalty import ShortJobPenalty
from tests.control_plane import ControlPlane
from armada_tpu.server import JobSubmitItem, QueueRecord

CFG = SchedulingConfig(shape_bucket=32)
F = CFG.resource_list_factory()


def spec(jid, queue="q", cpu="8"):
    return JobSpec(
        id=jid, queue=queue, resources=F.from_mapping({"cpu": cpu, "memory": "2"})
    )


# --- config parsing ----------------------------------------------------------


def test_parse_duration():
    assert parse_duration_s("5m") == 300.0
    assert parse_duration_s("90s") == 90.0
    assert parse_duration_s("1h30m") == 5400.0
    assert parse_duration_s("250ms") == 0.25
    assert parse_duration_s(45) == 45.0
    assert parse_duration_s("") == 0.0
    with pytest.raises(ValueError):
        parse_duration_s("5parsecs")


def test_pool_cutoff_from_yaml_dict():
    cfg = scheduling_config_from_dict(
        {"pools": [{"name": "default", "shortJobPenaltyCutoff": "2m"}]}
    )
    assert cfg.short_job_penalty_cutoffs() == {"default": 120.0}


# --- the predicate (short_job_penalty.go ShouldApplyPenalty) ----------------


def _finished_job(jid="j", pool="default", running_ns=1_000, preempted=False):
    run = JobRun(
        id="r-" + jid,
        job_id=jid,
        node_id="n0",
        pool=pool,
        running=True,
        running_ns=running_ns,
        succeeded=not preempted,
        preempted=preempted,
        run_attempted=True,
    )
    return Job(
        spec=spec(jid), queued=False, succeeded=not preempted, runs=(run,)
    )


def test_applies_within_window_only():
    p = ShortJobPenalty({"default": 60.0})
    job = _finished_job(running_ns=int(1e9))
    assert p.applies(job, int(30e9))  # 29s after start < 60s
    assert not p.applies(job, int(62e9))  # window lapsed
    # preempted runs never count (short_job_penalty.go:44)
    assert not p.applies(_finished_job(preempted=True), int(30e9))
    # non-terminal jobs never count
    running = Job(spec=spec("r"), queued=False, runs=(JobRun(
        id="rr", job_id="r", node_id="n0", running=True, running_ns=int(1e9)
    ),))
    assert not p.applies(running, int(30e9))
    # other pools are uncapped
    assert not p.applies(_finished_job(pool="other"), int(30e9))
    # disabled when no cutoffs
    assert not ShortJobPenalty({}).applies(job, int(30e9))


# --- kernel: penalty shifts candidate ordering ------------------------------


def test_penalty_deprioritises_churning_queue():
    nodes = [
        NodeSpec(
            id="n0",
            pool="default",
            total_resources=F.from_mapping({"cpu": "8", "memory": "32"}),
        )
    ]
    queues = [Queue("qa"), Queue("qb")]
    jobs = [spec("ja", "qa"), spec("jb", "qb")]  # only one fits

    # Baseline tie breaks toward the first queue index (qa).
    base = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=queues, queued_jobs=jobs
    )
    assert "ja" in base.scheduled and "jb" not in base.scheduled

    # qa recently churned a short job -> its ordering cost includes the
    # penalty, so qb goes first.
    pen = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=queues,
        queued_jobs=jobs,
        queue_penalty={"qa": F.from_mapping({"cpu": "8", "memory": "2"}).atoms},
    )
    assert "jb" in pen.scheduled and "ja" not in pen.scheduled
    assert pen.queue_stats["qa"]["short_job_penalty"] > 0.0
    assert pen.queue_stats["qb"]["short_job_penalty"] == 0.0


# --- end to end: retention, charging, sweep ---------------------------------


def test_short_job_charges_queue_then_expires(tmp_path):
    cfg = SchedulingConfig(
        shape_bucket=32,
        enable_assertions=True,
        pools=(PoolConfig("default", short_job_penalty_cutoff_s=60.0),),
    )
    cp = ControlPlane.build(
        tmp_path,
        config=cfg,
        executor_specs={"ex1": (1, "8", "32")},
        runtime_s=1.0,  # jobs exit almost immediately
    )
    cp.server.create_queue(QueueRecord("qa"))
    cp.server.create_queue(QueueRecord("qb"))
    ex = cp.executors[0]
    (ja,) = cp.server.submit_jobs(
        "qa", "js", [JobSubmitItem(resources={"cpu": "8", "memory": "2"})]
    )
    ex.run_once()
    cp.ingest()
    cp.scheduler.cycle()
    cp.ingest()
    ex.run_once()
    # report RUNNING first (running_ns must materialize -- a job that never
    # reported running has no RunningTime, exactly like the reference), then
    # run to completion and report success
    ex.cluster.tick(0.5)
    ex.report_cycle()
    cp.ingest()
    cp.scheduler.cycle()
    ex.cluster.tick(2.0)
    ex.report_cycle()
    ex.cleanup()
    cp.ingest()
    res = cp.scheduler.cycle()
    assert res.events_by_kind().get("job_succeeded") == 1
    cp.ingest()
    cp.scheduler.cycle()

    # terminal but retained: the penalty window keeps it in the JobDb
    job = cp.jobdb.read_txn().get(ja)
    assert job is not None and job.in_terminal_state()
    assert cp.scheduler.short_job_penalty.applies(job, cp.scheduler.now_ns())

    # while the window lasts, qa's cost carries the penalty: with one slot
    # free and one job per queue, qb wins the tie it would otherwise lose
    (ja2,) = cp.server.submit_jobs(
        "qa", "js", [JobSubmitItem(resources={"cpu": "8", "memory": "2"})]
    )
    (jb,) = cp.server.submit_jobs(
        "qb", "js", [JobSubmitItem(resources={"cpu": "8", "memory": "2"})]
    )
    cp.ingest()
    res2 = cp.scheduler.cycle()
    leased = {
        ev.job_run_leased.job_id
        for s in res2.published
        for ev in s.events
        if ev.WhichOneof("event") == "job_run_leased"
    }
    assert leased == {jb}

    # after the window lapses the sweep drops the finished job
    cp.clock.advance(120.0)
    cp.scheduler.cycle()
    assert cp.jobdb.read_txn().get(ja) is None
    cp.close()
