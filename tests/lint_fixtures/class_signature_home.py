# Fixture for rule `class-signature-home` (linted under armada_tpu/
# scheduler/): scheduling-class identity lives in ONE place
# (core/keys.class_signature) -- a second hand-rolled signature diverged
# on the excluded node-id label and crashed validation (round 5).  The
# rule anchors on FIELD-READ provenance, not textual cloning: the TP
# tuple combines three class-identity fields of ONE job (one of them
# through a project helper -- v3 field-read flow across the boundary);
# the twin is syntactically IDENTICAL but splits its reads across two
# objects, so no single root reaches the signature threshold.


def selector_items(job):
    return tuple(sorted(job.node_selector.items()))


def index(jobs, others):
    out = {}
    for job, other in zip(jobs, others):
        sel = selector_items(job)
        tol = tuple(job.tolerations)
        pc = job.priority_class
        sel2 = selector_items(other)
        tol2 = tuple(other.tolerations)
        pc2 = job.priority_class
        key = (sel, tol, pc)  # TP
        alt = (sel2, tol2, pc2)  # twin
        out[key] = alt
    return out
