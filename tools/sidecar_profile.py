"""Profile the sidecar's steady direct cycle on CPU: sync/round splits over
warmed cycles, then a cProfile of 3 more -- the methodology behind
docs/bench.md's round-6 host-side ablation (whole-cycle differencing is
useless when the CPU kernel's variance exceeds the host-side trim being
measured).  Scale knobs: PJOBS, PNODES, PQUEUES, PRUNS, PBURST; e.g.
PJOBS=1000000 PNODES=50000 PRUNS=25000 python tools/sidecar_profile.py.

The sync/round split is read from the CYCLE TRACE ring (ops/trace.py):
each handle_sync/handle_round records a cycle tree rooted at the SESSION
methods (apply_sync/schedule_round), and this tool reports those root
durations -- the same stage-split source of truth bench.py's stage_*_s
keys, /healthz's trace block and `armadactl trace` read, instead of a
second set of ad-hoc timers that could drift from it.  Scope note: the
session roots exclude the thin wire shims around them -- handle_sync's
executor/queue/bid proto parsing (jobs convert INSIDE apply_sync, which
dominates) and handle_round's response assembly (~1k RoundLease appends
+ stats JSON).  Those slices still show in the cProfile section below;
the r6-era perf_counter numbers included them, so per-cycle totals here
read a few ms lower than that baseline at equal cost."""
import cProfile
import io
import os
import pstats
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def main():
    jobs = int(os.environ.get("PJOBS", 200_000))
    nodes = int(os.environ.get("PNODES", 10_000))
    queues = int(os.environ.get("PQUEUES", 64))
    runs = int(os.environ.get("PRUNS", nodes // 2))
    burst = int(os.environ.get("PBURST", 1_000))

    import dataclasses

    from armada_tpu.events.convert import job_spec_to_proto
    from armada_tpu.models.synthetic import synthetic_world
    from armada_tpu.rpc import rpc_pb2 as pb
    from armada_tpu.scheduler.executors import ExecutorSnapshot
    from armada_tpu.scheduler.sidecar import ScheduleSidecar

    t0 = time.perf_counter()
    config, nodes_l, queues_l, specs, running, spec_factory = (
        bench.synthetic_world(
            num_nodes=nodes,
            num_jobs=jobs,
            num_queues=queues,
            num_runs=runs,
            seed=7,
            shape_bucket=max(8192, 4 * burst),
        )
        if hasattr(bench, "synthetic_world")
        else synthetic_world(
            num_nodes=nodes,
            num_jobs=jobs,
            num_queues=queues,
            num_runs=runs,
            seed=7,
            shape_bucket=max(8192, 4 * burst),
        )
    )
    config = dataclasses.replace(
        config,
        incremental_problem_build=True,
        maximum_scheduling_rate=1e9,
        maximum_per_queue_scheduling_rate=1e9,
        maximum_scheduling_burst=burst,
        maximum_per_queue_scheduling_burst=burst,
    )
    now0 = 10**12
    clock = [now0]
    sidecar = ScheduleSidecar(config, clock_ns=lambda: clock[0])
    sid = sidecar.create_session("prof")
    session = sidecar.session(sid)

    def state_of_spec(s):
        return pb.JobState(
            job_id=s.id,
            queue=s.queue,
            jobset="bench",
            spec=job_spec_to_proto(s),
            priority=s.priority,
            queued=True,
            validated=True,
            submit_time=s.submit_time,
        )

    def state_of_run(r, i):
        m = state_of_spec(r.job)
        m.queued = False
        pc = config.priority_class(r.job.priority_class)
        m.run.MergeFrom(
            pb.JobRunState(
                run_id=f"run{i:08d}",
                node_id=r.node_id,
                node_name=r.node_id,
                pool="default",
                scheduled_at_priority=pc.priority,
                has_scheduled_at_priority=True,
                running=True,
                running_ns=now0 - 10**9,
            )
        )
        return m

    n_ex = 10
    per = (len(nodes_l) + n_ex - 1) // n_ex
    executors = [
        ExecutorSnapshot(
            id=f"ex{e}",
            pool="default",
            nodes=tuple(nodes_l[e * per : (e + 1) * per]),
            last_update_ns=now0,
        )
        for e in range(n_ex)
    ]
    session.apply_sync(executors=executors, queues=queues_l)
    chunk = 50_000
    for lo in range(0, len(specs), chunk):
        sidecar.handle_sync(
            pb.SyncStateRequest(
                session_id=sid,
                jobs=[state_of_spec(s) for s in specs[lo : lo + chunk]],
            )
        )
    for lo in range(0, len(running), chunk):
        sidecar.handle_sync(
            pb.SyncStateRequest(
                session_id=sid,
                jobs=[
                    state_of_run(r, lo + i)
                    for i, r in enumerate(running[lo : lo + chunk])
                ],
            )
        )
    print(f"setup {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    from armada_tpu.models.xfer import TRANSFER_STATS
    from armada_tpu.ops.trace import recorder as trace_recorder

    rec = trace_recorder()
    if not rec.enabled:
        print(
            "warning: ARMADA_TRACE=0 disables cycle tracing -- the "
            "sync/round splits below will read 0",
            file=sys.stderr,
        )

    def _ring_duration(kind: str) -> float:
        """Root duration of the newest ring entry of this kind -- the
        trace-span timing of the call that just returned."""
        for t in reversed(rec.last()):
            if t.kind == kind:
                return t.root.dur_s
        return 0.0

    def cycle():
        clock[0] += 10**9
        fresh = spec_factory(burst, clock[0] / 1e9)
        states = [state_of_spec(s) for s in fresh]
        TRANSFER_STATS.reset()
        sidecar.handle_sync(pb.SyncStateRequest(session_id=sid, jobs=states))
        t_sync = _ring_duration("sync")
        xs_sync = TRANSFER_STATS.snapshot()
        resp = sidecar.handle_round(
            pb.ScheduleRoundRequest(session_id=sid, now_ns=clock[0])
        )
        t_round = _ring_duration("round")
        xs = TRANSFER_STATS.snapshot()
        xs["sync_up_transfers"] = xs_sync["up_transfers"]
        xs["sync_up_bytes"] = xs_sync["up_bytes"]
        return t_sync, t_round, len(resp.scheduled), xs

    # Pipeline A/B over the SAME live session (the sidecar reads
    # ARMADA_PIPELINE / ARMADA_PIPELINE_PREFETCH per call): warmed cycles
    # per arm, with per-cycle device-transfer counters split by phase -- on
    # the real tunnel, upload work counted in the SYNC phase overlaps the
    # caller's cycle instead of the round's critical path, so the
    # sync-vs-round split is the number to watch even on a CPU host.
    # Arms: pipelined+prefetch (the TPU-shaped config, scatter forced on),
    # pipelined (CPU default: shadow order only), sequential.  The
    # operator's own env is restored afterwards so the cProfile below
    # measures the configuration that was asked for.
    env0 = {
        k: os.environ.get(k)
        for k in ("ARMADA_PIPELINE", "ARMADA_PIPELINE_PREFETCH")
    }
    for _ in range(2):
        cycle()
    for arm, label in (
        (("1", "1"), "pipelined+prefetch"),
        (("1", None), "pipelined"),
        (("0", "0"), "sequential"),
    ):
        os.environ["ARMADA_PIPELINE"] = arm[0]
        if arm[1] is None:
            os.environ.pop("ARMADA_PIPELINE_PREFETCH", None)
        else:
            os.environ["ARMADA_PIPELINE_PREFETCH"] = arm[1]
        cycle()  # settle the arm (first cycle pays any carried-over state)
        for _ in range(3):
            ts, tr, n, xs = cycle()
            print(
                f"[{label}] sync {ts:.3f}s round {tr:.3f}s total "
                f"{ts+tr:.3f}s sched {n} | sync-up "
                f"{xs['sync_up_transfers']}x/{xs['sync_up_bytes']/1e6:.2f}MB "
                f"cycle-up {xs['up_transfers']}x/{xs['up_bytes']/1e6:.2f}MB "
                f"down {xs['down_transfers']}x/{xs['down_bytes']/1e6:.3f}MB",
                file=sys.stderr,
            )
    for k, v in env0.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    pr = cProfile.Profile()
    pr.enable()
    for _ in range(3):
        cycle()
    pr.disable()
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(45)
    print(s.getvalue())


if __name__ == "__main__":
    main()
