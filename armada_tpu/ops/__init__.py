"""Device-side scheduling primitives (pure jittable functions).

These are the TPU-native equivalents of the reference's hot computational kernels:
DRF cost/share computation (scheduling/fairness/fairness.go), fair-share
water-filling (scheduling/context/scheduling.go:188-300), NodeDb fit predicates
(nodedb/nodematching.go) and bin-packing node selection (nodedb/nodedb.go:615-800).
Everything operates on dense tensors in resolution units; no Python objects.
"""

from armada_tpu.ops.fairness import (
    unweighted_drf_cost,
    weighted_drf_cost,
    fair_shares,
)
from armada_tpu.ops.fit import (
    dynamic_fit,
    static_fit,
    job_fit,
    allocatable_from_used,
)
from armada_tpu.ops.packing import (
    member_capacity,
    node_packing_score,
    select_best_node,
    select_gang_nodes,
    select_gang_nodes_compact,
    bind_to_node,
    unbind_from_node,
)

__all__ = [
    "unweighted_drf_cost",
    "weighted_drf_cost",
    "fair_shares",
    "dynamic_fit",
    "static_fit",
    "job_fit",
    "allocatable_from_used",
    "member_capacity",
    "node_packing_score",
    "select_best_node",
    "select_gang_nodes",
    "select_gang_nodes_compact",
    "bind_to_node",
    "unbind_from_node",
]
