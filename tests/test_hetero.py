"""Heterogeneity-aware scheduling, end to end (round 20).

The tentpole surfaces under one roof: the annotation parser and its
validation gate, key identity absorbing the type axis, SubmitChecker's
unknown-type rejection, the kernel's whitelist + throughput-bias placement
on hand-built worlds, bit-identity of single-type fleets with pre-hetero
decisions, cache/commit_k bit-equality on a type-sensitive synthetic
problem (the docs/lint.md ledger row), the explain pass's type-mismatch
attribution + per-type fragmentation, and a heterogeneous soak smoke.

The statistical parity legs (mixed fleets vs the independent sequential
oracle, scheduled AND preempted sets over many seeds) live in
tests/test_parity_full.py::test_hetero_*.
"""

import dataclasses

import numpy as np
import pytest

from armada_tpu.core.config import PoolConfig, SchedulingConfig
from armada_tpu.core.keys import (
    TYPE_BIAS_SCALE,
    NodeType,
    SchedulingKey,
    class_signature,
    static_fit_matrix,
    type_feasible,
    type_score_tables,
)
from armada_tpu.core.types import (
    NODE_TYPE_SCORES_ANNOTATION,
    JobSpec,
    NodeSpec,
    Queue,
    parse_node_type_scores,
)
from armada_tpu.models import explain as explain_mod
from armada_tpu.models import run_scheduling_round

# The lifted round-cap fraction mirrors test_explain: attribution tests
# need every queued job ATTEMPTED, and it is bit-neutral for worlds that
# never fill the pool.
CFG = SchedulingConfig(
    shape_bucket=32, maximum_resource_fraction_to_schedule={}
)
F = CFG.resource_list_factory()


def node(nid, cpu=8, mem=32, node_type=""):
    return NodeSpec(
        id=nid,
        pool="default",
        total_resources=F.from_mapping({"cpu": cpu, "memory": mem}),
        node_type=node_type,
    )


def job(jid, cpu=2, mem=2, sub=0.0, **kw):
    return JobSpec(
        id=jid,
        queue=kw.pop("queue", "qa"),
        submit_time=float(sub),
        resources=F.from_mapping({"cpu": cpu, "memory": mem}),
        **kw,
    )


def sched_key(**kw):
    kw.setdefault("priority", 0)
    return SchedulingKey(
        resources=(), node_selector=(), tolerations=(),
        priority_class="d", **kw,
    )


def hw(name):
    return NodeType(taints=(), indexed_labels=(), hw_type=name)


# --- the annotation parser ---------------------------------------------------


def test_parse_node_type_scores_canonical():
    got = parse_node_type_scores("v5e=2.0, v4=1 ,v6=4")
    assert got == (("v4", 1.0), ("v5e", 2.0), ("v6", 4.0))  # sorted
    assert parse_node_type_scores("") == ()
    assert parse_node_type_scores("  ") == ()


@pytest.mark.parametrize(
    "text",
    [
        "v5e",  # missing =
        "v5e=fast",  # non-numeric
        "v5e=0",  # throughput must be > 0
        "v5e=-1",
        "=2.0",  # empty type name
        "v5e=1,v5e=2",  # duplicate type
    ],
)
def test_parse_node_type_scores_rejects(text):
    with pytest.raises(ValueError):
        parse_node_type_scores(text)


def test_validation_rejects_malformed_annotation():
    from armada_tpu.server.submit import JobSubmitItem
    from armada_tpu.server.validation import ValidationError, validate_submission

    bad = JobSubmitItem(
        resources={"cpu": "1", "memory": "1"},
        annotations={NODE_TYPE_SCORES_ANNOTATION: "v5e=fast"},
    )
    with pytest.raises(ValidationError, match="item 0"):
        validate_submission([bad], CFG)
    ok = JobSubmitItem(
        resources={"cpu": "1", "memory": "1"},
        annotations={NODE_TYPE_SCORES_ANNOTATION: "v5e=2.0"},
    )
    validate_submission([ok], CFG)  # parses clean


# --- key identity + tables ---------------------------------------------------


def test_class_signature_absorbs_type_axis():
    a = job("j1")
    b = dataclasses.replace(a, node_type_scores=(("v5e", 2.0),))
    c = dataclasses.replace(a, node_type_scores=(("v5e", 4.0),))
    label = CFG.node_id_label
    assert class_signature(a, label) != class_signature(b, label)
    assert class_signature(b, label) != class_signature(c, label)  # weights
    assert class_signature(b, label) == class_signature(
        dataclasses.replace(b, id="other"), label
    )


def test_type_feasible_whitelist():
    insensitive = sched_key()
    sensitive = sched_key(type_scores=(("v5e", 2.0),))
    assert type_feasible(insensitive, hw("v5e"))
    assert type_feasible(insensitive, hw("v4"))
    assert type_feasible(sensitive, hw("v5e"))
    assert not type_feasible(sensitive, hw("v4"))  # whitelist excludes


def test_type_score_tables_row_interning_and_bias():
    types = [hw(""), hw("v4"), hw("v5e")]
    keys = [
        sched_key(),
        sched_key(type_scores=(("v4", 1.0), ("v5e", 2.0))),
        sched_key(priority=1, type_scores=(("v4", 1.0), ("v5e", 2.0))),
        sched_key(type_scores=(("v5e", 4.0),)),
    ]
    key_row, bias = type_score_tables(keys, types, len(keys), len(types))
    assert key_row[0] == 0  # insensitive keys share the all-zero row
    assert key_row[1] == key_row[2] != 0  # identical maps intern one row
    assert key_row[3] not in (0, key_row[1])
    assert np.all(bias[0] == 0.0)
    r1 = bias[key_row[1]]
    # thr=1 -> zero bias; thr=2 -> negative (preferred); a hardware type
    # the map does not name gets 0 (infeasibility is the compat gate's
    # job, never the bias row's)
    assert r1[1] == np.float32(0.0)
    assert r1[2] == np.float32((1.0 / 2.0 - 1.0) * TYPE_BIAS_SCALE)
    assert r1[0] == np.float32(0.0)
    # no sensitive key at all -> TR == 1 (the kernel's pre-hetero body)
    _, bias0 = type_score_tables(keys[:1], types, 1, len(types))
    assert bias0.shape[0] == 1


def test_static_fit_matrix_pre_type_skips_whitelist():
    types = [hw("v4"), hw("v5e")]
    sens = sched_key(type_scores=(("v5e", 2.0),))
    post = static_fit_matrix([sens], types)
    pre = static_fit_matrix([sens], types, pre_type=True)
    assert not post[0, 0] and post[0, 1]
    assert pre[0, 0] and pre[0, 1]  # pre-type: the whitelist is ignored


# --- SubmitChecker -----------------------------------------------------------


def test_submitcheck_unknown_type_rejected_with_words():
    from armada_tpu.scheduler.executors import ExecutorSnapshot
    from armada_tpu.scheduler.submitcheck import SubmitChecker

    cfg = SchedulingConfig(shape_bucket=32, pools=(PoolConfig("default"),))
    checker = SubmitChecker(cfg)
    checker.update_executors(
        [
            ExecutorSnapshot(
                id="ex1",
                pool="default",
                nodes=(
                    node("n0", node_type="v4"),
                    node("n1", node_type=""),
                ),
                last_update_ns=1,
            )
        ]
    )
    res = checker.check_gang([job("j1", node_type_scores=(("v9", 2.0),))])
    assert not res.ok
    assert "v9" in res.reason and "no such node exists" in res.reason
    # a map naming an existing type passes
    assert checker.check_gang(
        [job("j2", node_type_scores=(("v4", 2.0),))]
    ).ok
    # untyped jobs are untouched
    assert checker.check_gang([job("j3")]).ok


# --- kernel placement: whitelist + bias, hand-built --------------------------


def test_bias_steers_placement_to_fast_type():
    """Unbiased best-fit prefers the smaller (more packed) node; a 4x
    throughput on the bigger node's type must flip the pick -- the bias
    outweighs any packing-score difference by construction (scale 1024)."""
    nodes = [
        node("slow", cpu=8, mem=32, node_type="v4"),
        node("fast", cpu=32, mem=128, node_type="v6"),
    ]
    queues = [Queue("qa", 1.0)]
    plain = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=queues,
        queued_jobs=[job("j1")], collect_stats=False,
    )
    assert plain.scheduled == {"j1": "slow"}  # best-fit baseline direction
    biased = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=queues,
        queued_jobs=[
            job("j1", node_type_scores=(("v4", 1.0), ("v6", 4.0)))
        ],
        collect_stats=False,
    )
    assert biased.scheduled == {"j1": "fast"}


def test_whitelist_excludes_unnamed_types():
    nodes = [
        node("a", node_type="v4"),
        node("b", node_type="v6"),
        node("c", node_type=""),
    ]
    queues = [Queue("qa", 1.0)]
    out = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=queues,
        queued_jobs=[
            job("j1", node_type_scores=(("v6", 1.0),)),
            job("j2", sub=1.0, node_type_scores=(("v9", 1.0),)),
        ],
        collect_stats=False,
    )
    assert out.scheduled.get("j1") == "b"
    assert "j2" in out.failed  # whitelists an absent type


def test_single_type_fleet_bit_identical_to_untyped():
    """Types without type-sensitive jobs change NOTHING: same decisions as
    the untyped fleet (TR == 1 compiles the pre-hetero body)."""
    rng = np.random.default_rng(5)
    untyped = [
        node(f"n{i}", cpu=int(rng.choice([8, 16]))) for i in range(12)
    ]
    typed = [dataclasses.replace(n, node_type="v5e") for n in untyped]
    queues = [Queue("qa", 1.0), Queue("qb", 2.0)]
    jobs = [
        job(f"j{i:03d}", cpu=int(rng.choice([1, 2, 4])),
            queue="qa" if i % 3 else "qb", sub=i)
        for i in range(40)
    ]
    a = run_scheduling_round(
        CFG, pool="default", nodes=untyped, queues=queues,
        queued_jobs=jobs, collect_stats=False,
    )
    b = run_scheduling_round(
        CFG, pool="default", nodes=typed, queues=queues,
        queued_jobs=jobs, collect_stats=False,
    )
    assert a.scheduled == b.scheduled
    assert a.preempted == b.preempted
    assert a.failed == b.failed


def test_hetero_cache_and_commit_k_bit_equal():
    """The docs/lint.md ledger leg: on a type-sensitive synthetic problem
    the per-key fit cache (which refuses trow != 0 candidates) and the
    multi-commit kernel (whose extension lanes truncate sensitive picks)
    must stay bit-identical to the single-commit uncached body."""
    import jax.numpy as jnp

    from armada_tpu.models.fair_scheduler import schedule_round as sr
    from armada_tpu.models.problem import SchedulingProblem
    from armada_tpu.models.synthetic import synthetic_problem

    problem, meta = synthetic_problem(
        num_nodes=64, num_gangs=300, num_queues=8, num_runs=40,
        num_node_types=4, type_sensitive_frac=0.5,
        global_burst=200, perq_burst=60, seed=3, max_gang_cardinality=3,
    )
    assert problem.type_bias.shape[0] > 1  # the hetero body really compiled
    dev = SchedulingProblem(*(jnp.asarray(a) for a in problem))
    kw = dict(
        num_levels=meta["num_levels"], max_slots=meta["max_slots"],
        slot_width=meta["slot_width"],
    )
    base = sr(dev, **kw, cache_slots=0, commit_k=1)
    for cs, ck in ((8, 1), (0, 4), (8, 8)):
        got = sr(dev, **kw, cache_slots=cs, commit_k=ck)
        for name in base._fields:
            if name == "kernel_iters":
                continue  # multi-commit legitimately shrinks trips
            np.testing.assert_array_equal(
                np.asarray(getattr(base, name)),
                np.asarray(getattr(got, name)),
                err_msg=f"cache_slots={cs} K={ck}: diverged on {name}",
            )


# --- explain: type-mismatch + per-type fragmentation -------------------------


@pytest.fixture
def explain_armed(monkeypatch):
    monkeypatch.setenv("ARMADA_EXPLAIN_INTERVAL", "1")
    explain_mod.reset_cadence()
    yield


def test_explain_type_mismatch_partition(explain_armed):
    """Hand-built mixed fleet: the whitelisted-out job reads type-mismatch,
    the nowhere-fits job reads shape-infeasible (shape dominates type),
    and per-type fragmentation rows appear for every fleet type."""
    nodes = [
        node("a0", cpu=8, node_type="v4"),
        node("a1", cpu=8, node_type="v4"),
        node("b0", cpu=2, mem=4, node_type="v6"),
    ]
    queues = [Queue("qa", 1.0)]
    jobs = [
        job("fits", cpu=1, mem=1, sub=0),
        # needs cpu=4: fits a v4 node fine, but the whitelist only admits
        # v6 whose one node is too small -> type-mismatch
        job("typed-out", cpu=4, mem=4, sub=1,
            node_type_scores=(("v6", 2.0),)),
        # fits NO node even empty -- and carries a map, which must NOT
        # demote the dominant static reason
        job("too-big", cpu=99, mem=99, sub=2,
            node_type_scores=(("v4", 2.0),)),
    ]
    out = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=queues,
        queued_jobs=jobs, collect_stats=False,
    )
    reasons = dict(out.explain.iter_job_reasons())
    assert "fits" in out.scheduled
    assert reasons["typed-out"] == "type-mismatch"
    assert out.explain.failed_counts["type-mismatch"] == 1
    # the nowhere-fits job is retired before any attempt (shape
    # infeasibility is static), so it reads shape-infeasible in the
    # PENDING vector -- shape dominated the type map it also carried
    assert out.explain.counts["shape-infeasible"] == 1
    assert out.explain.pending_counts["shape-infeasible"] == 1
    assert out.explain.counts["type-mismatch"] == 1
    # per-type fragmentation: one row per fleet type, every resource
    by_type = out.explain.fragmentation_by_type
    assert set(by_type) == {"v4", "v6"}
    for row in by_type.values():
        for rname in F.names:
            assert 0.0 <= row[rname]["index"] <= 1.0
    assert "fragmentation_by_type" in out.explain.summary()


def test_explain_single_type_fleet_skips_by_type(explain_armed):
    out = run_scheduling_round(
        CFG, pool="default", nodes=[node("n0"), node("n1")],
        queues=[Queue("qa", 1.0)], queued_jobs=[job("j1")],
        collect_stats=False,
    )
    assert out.explain is not None
    assert out.explain.fragmentation_by_type == {}
    assert "fragmentation_by_type" not in out.explain.summary()


def test_metrics_type_fragmentation_stale_label_removal():
    import prometheus_client

    from armada_tpu.scheduler.metrics import SchedulerMetrics

    m = SchedulerMetrics(registry=prometheus_client.CollectorRegistry())

    def fake_explain(by_type):
        return type(
            "E",
            (),
            {
                "queue_counts": {},
                "fragmentation": {},
                "fragmentation_by_type": by_type,
            },
        )()

    m._observe_explain(
        "default",
        fake_explain(
            {
                "v4": {"cpu": {"index": 0.5}},
                "v6": {"cpu": {"index": 0.25}},
            }
        ),
    )
    assert ("default", "v4", "cpu") in m._type_frag_labels
    assert ("default", "v6", "cpu") in m._type_frag_labels
    # the fleet went homogeneous: the per-type series must disappear
    m._observe_explain("default", fake_explain({}))
    assert not m._type_frag_labels


# --- loadgen / soak ----------------------------------------------------------


def test_workload_hetero_mix_deterministic_and_parsable():
    from armada_tpu.loadgen.workload import MixConfig, SubmitOp, WorkloadGenerator

    mix = MixConfig(
        node_types=("v4", "v5e"), type_sensitive_fraction=0.5,
        cancel_weight=0.0, reprioritize_weight=0.0,
    )
    a = WorkloadGenerator(mix, seed=11).next_ops(200)
    b = WorkloadGenerator(mix, seed=11).next_ops(200)
    seen = 0
    for op_a, op_b in zip(a, b):
        if not isinstance(op_a, SubmitOp):
            continue
        for it_a, it_b in zip(op_a.items, op_b.items):
            assert it_a.annotations == it_b.annotations  # seed-deterministic
            raw = it_a.annotations.get(NODE_TYPE_SCORES_ANNOTATION)
            if raw:
                seen += 1
                parsed = parse_node_type_scores(raw)
                assert parsed  # round-trips through the production parser
                assert {t for t, _ in parsed} <= {"v4", "v5e"}
    assert seen > 0


@pytest.mark.slow
def test_soak_hetero_fleet_smoke(tmp_path):
    """A short heterogeneous soak: typed fake nodes, type-sensitive
    submits riding the real annotation path, zero lifecycle violations."""
    from armada_tpu.loadgen.soak import SoakConfig, run_soak

    report = run_soak(
        SoakConfig(
            window_s=6.0,
            target_eps=30.0,
            num_nodes=4,
            num_queues=2,
            drain_s=2.0,
            cycle_interval_s=0.2,
            schedule_interval_s=0.5,
            seed=7,
            node_types=("v4", "v5e"),
            type_sensitive_fraction=0.4,
        ),
        str(tmp_path),
    )
    assert report["ok"], report
    assert report["violations"] == 0
    assert report["events"].get("type_sensitive", 0) > 0
    assert report["jobs"]["leased"] > 0
