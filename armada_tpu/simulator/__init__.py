"""Discrete-event scheduler simulator (cmd/simulator +
internal/scheduler/simulator equivalent): drives the production round kernel
through virtual time from declarative cluster/workload YAML specs."""

from armada_tpu.simulator.simulator import CycleStats, SimulationResult, Simulator
from armada_tpu.simulator.spec import (
    ClusterSpec,
    ClusterTemplate,
    JobTemplate,
    NodeTemplate,
    QueueSpec,
    RepeatDetails,
    ShiftedExponential,
    WorkloadSpec,
    cluster_spec_from_dict,
    cluster_spec_from_yaml,
    parse_duration,
    workload_spec_from_dict,
    workload_spec_from_yaml,
)
from armada_tpu.simulator.sink import JsonlSink, write_parquet

__all__ = [
    "Simulator",
    "SimulationResult",
    "CycleStats",
    "ClusterSpec",
    "ClusterTemplate",
    "NodeTemplate",
    "WorkloadSpec",
    "QueueSpec",
    "JobTemplate",
    "RepeatDetails",
    "ShiftedExponential",
    "parse_duration",
    "cluster_spec_from_dict",
    "cluster_spec_from_yaml",
    "workload_spec_from_dict",
    "workload_spec_from_yaml",
    "JsonlSink",
    "write_parquet",
]
