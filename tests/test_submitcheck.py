"""SubmitChecker tests: static schedulability at validation time.

Modeled on the reference's submitcheck tests (internal/scheduler/
submitcheck_test.go): jobs/gangs that can never fit are rejected with a
reason; feasible ones validate with the pools they fit in.
"""

import pytest

from armada_tpu.core.config import PoolConfig, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, Taint, Toleration
from armada_tpu.scheduler.executors import ExecutorSnapshot
from armada_tpu.scheduler.submitcheck import SubmitChecker

CFG = SchedulingConfig(
    shape_bucket=32,
    pools=(PoolConfig("cpu-pool"), PoolConfig("gpu-pool")),
)
F = CFG.resource_list_factory()


def snapshot(ex_id="ex1", pool="cpu-pool", num=2, cpu="8", mem="32", taints=(), labels=None):
    nodes = tuple(
        NodeSpec(
            id=f"{ex_id}-n{i}",
            pool=pool,
            executor=ex_id,
            total_resources=F.from_mapping({"cpu": cpu, "memory": mem}),
            taints=tuple(taints),
            labels=labels or {},
        )
        for i in range(num)
    )
    return ExecutorSnapshot(id=ex_id, pool=pool, nodes=nodes, last_update_ns=1)


def job(cpu="2", mem="2", **kw):
    return JobSpec(
        id=kw.pop("id", "j1"),
        queue="q",
        resources=F.from_mapping({"cpu": cpu, "memory": mem}),
        **kw,
    )


@pytest.fixture
def checker():
    c = SubmitChecker(CFG)
    c.update_executors([snapshot()])
    return c


def test_feasible_job_passes_with_pools(checker):
    res = checker.check_gang([job()])
    assert res.ok and res.pools == ("cpu-pool",)


def test_oversized_job_rejected_with_gap(checker):
    res = checker.check_gang([job(cpu="999")])
    assert not res.ok
    assert "exceeds every node's capacity" in res.reason
    assert "cpu" in res.reason


def test_gang_larger_than_fleet_rejected(checker):
    members = [job(cpu="4", id=f"g{i}") for i in range(5)]  # fleet fits 4
    res = checker.check_gang(members)
    assert not res.ok
    assert "4 of 5" in res.reason


def test_gang_that_fits_passes(checker):
    members = [job(cpu="4", id=f"g{i}") for i in range(4)]
    assert checker.check_gang(members).ok


def test_selector_mismatch_rejected(checker):
    res = checker.check_gang([job(node_selector={"zone": "mars"})])
    assert not res.ok


def test_selector_match_and_taints():
    c = SubmitChecker(CFG)
    c.update_executors(
        [
            snapshot(
                taints=(Taint("dedicated", "ml", "NoSchedule"),),
                labels={"zone": "east"},
            )
        ]
    )
    # intolerant job blocked by the taint
    assert not c.check_gang([job()]).ok
    # tolerating + matching selector passes
    ok = c.check_gang(
        [
            job(
                tolerations=(Toleration("dedicated", "Equal", "ml", "NoSchedule"),),
                node_selector={"zone": "east"},
            )
        ]
    )
    assert ok.ok


def test_requested_pool_must_exist(checker):
    res = checker.check_gang([job(pools=("gpu-pool",))])
    assert not res.ok and "gpu-pool" in res.reason


def test_multi_pool_fleet_reports_fitting_pools():
    c = SubmitChecker(CFG)
    c.update_executors(
        [snapshot("ex1", "cpu-pool"), snapshot("ex2", "gpu-pool", cpu="16")]
    )
    res = c.check_gang([job(cpu="12")])
    assert res.ok and res.pools == ("gpu-pool",)
    res = c.check_gang([job(cpu="2")])
    assert res.pools == ("cpu-pool", "gpu-pool")


def test_cache_invalidated_on_fleet_change():
    c = SubmitChecker(CFG)
    c.update_executors([snapshot(cpu="8")])
    assert not c.check_gang([job(cpu="12")]).ok
    c.update_executors([snapshot(cpu="16")])
    assert c.check_gang([job(cpu="12")]).ok


def test_scheduler_rejects_unschedulable_at_validation(tmp_path):
    """End-to-end: an impossible job fails fast instead of starving the
    queue behind a permanently-tripped round cap."""
    from armada_tpu.server import JobSubmitItem, QueueRecord
    from tests.control_plane import ControlPlane

    cp = ControlPlane.build(tmp_path)
    cp.server.create_queue(QueueRecord("q"))
    for ex in cp.executors:
        ex.run_once()
    big = cp.server.submit_jobs(
        "q", "mix", [JobSubmitItem(resources={"cpu": "999", "memory": "1"})]
    )
    small = cp.server.submit_jobs(
        "q", "mix", [JobSubmitItem(resources={"cpu": "2", "memory": "1"}) for _ in range(4)]
    )
    cp.ingest()
    cp.scheduler.cycle()
    cp.ingest()
    states = cp.job_states()
    assert states[big[0]] == "failed"
    # every small job leased in the same cycle -- no starvation
    assert all(states[j] == "leased" for j in small)
    cp.close()
