"""Publisher / Consumer over the native event log.

Equivalent surface to the reference's Pulsar plumbing:
  * `Publisher.publish` routes an EventSequence to a partition by hash of its
    (queue, jobset) key, chunking big sequences by max-events-per-message
    (internal/common/pulsarutils jobsetevents key routing + chunking,
    internal/scheduler/publisher.go:25-60).
  * `Publisher.publish_markers` writes one PartitionMarker to EVERY partition;
    a consumer that has seen all markers of a group knows it is read-fenced up
    to the publish point (publisher.go PublishMarkers:30-33,
    scheduler.go ensureDbUpToDate:1120).
  * `Consumer` tracks a per-partition position (byte offset); callers persist
    positions as their high-water mark (each materialized view's
    checkpoint/resume story, SURVEY.md section 5).
"""

from __future__ import annotations

import time
import uuid
import zlib
from typing import Callable, Iterable, NamedTuple, Optional, Sequence

from armada_tpu.eventlog.log import EventLog, Message
from armada_tpu.events import events_pb2 as pb


MARKER_KEY = b"\x00marker"


def jobset_key(queue: str, jobset: str) -> bytes:
    return f"{queue}/{jobset}".encode()


def partition_for_key(key: bytes, num_partitions: int) -> int:
    # Stable across processes (unlike hash()), cheap, uniform enough.
    return zlib.crc32(key) % num_partitions


class PublishedRef(NamedTuple):
    partition: int
    offset: int


class NotLeader(RuntimeError):
    """Publish rejected: this replica's log is a replica, not the log of
    record (cross-host HA).  Carries the leader's advertised address; the
    gRPC layer maps it to the retryable UNAVAILABLE."""

    def __init__(self, leader_address: str = ""):
        super().__init__(
            "not the leader"
            + (f"; leader at {leader_address}" if leader_address else "")
        )
        self.leader_address = leader_address


class DeposedEpoch(NotLeader):
    """Publish rejected by the epoch fence: the election record has moved
    past the generation this process last led at -- a successor exists, so
    appending here would fork the log its replicator is about to become a
    follower of.  Subclasses NotLeader so transports keep answering the
    retryable UNAVAILABLE."""

    def __init__(self, held: int, current: int):
        super().__init__()
        self.args = (
            f"deposed: publishing at epoch {held} but the election record "
            f"is at {current}",
        )
        self.held = held
        self.current = current


class Publisher:
    """Routes EventSequences to log partitions; the only write path to the log."""

    def __init__(
        self,
        log: EventLog,
        max_events_per_message: int = 1000,
        clock: Callable[[], float] = time.time,
    ):
        self._log = log
        self._max_events = max_events_per_message
        self._clock = clock
        # Replicated deployments (serve --replicate-log): () -> None (may
        # write) | leader address (must not).  Checked on EVERY publish --
        # this is the single choke point, so a follower's ExecutorApi /
        # ExecutorAdmin / queue-CRUD handlers can never append locally and
        # fork the log their replicator is tailing.
        self.write_gate = None
        # Epoch fence (elected deployments): `epoch_source` peeks the
        # election record's monotonic generation; `set_epoch` records the
        # generation this process last held leadership at (the scheduler
        # stamps it every leader cycle).  A publish whose held epoch is
        # older than the record's current one is from a DEPOSED leader --
        # rejected even if the write_gate's cached leadership view has not
        # caught up yet.  Both the address gate and the epoch fence sit on
        # this one choke point every append path shares.
        self.epoch_source = None
        self._epoch: Optional[int] = None
        # Publish wakeups (round 18): consumers register a callback fired
        # AFTER the batch is durably appended+fsynced, with the set of
        # partitions touched -- the ingestion pipelines' replacement for
        # their fixed idle poll (a shard sleeps until its partitions have
        # data, and wakes the instant they do).  Callbacks must be cheap and
        # non-raising; a slow hook would sit on every publish path.
        self._wakeups: list[Callable[[set], None]] = []

    def add_wakeup(self, hook: Callable[[set], None]) -> None:
        """Register a post-publish hook: hook(partitions_touched)."""
        self._wakeups.append(hook)

    def remove_wakeup(self, hook: Callable[[set], None]) -> None:
        try:
            self._wakeups.remove(hook)
        except ValueError:
            pass

    def _fire_wakeups(self, partitions: set) -> None:
        for hook in self._wakeups:
            try:
                hook(partitions)
            except Exception:  # noqa: BLE001 - a broken consumer must not
                pass  # fail the publish (the data is already durable)

    def set_epoch(self, generation: int) -> None:
        """Record the election generation this process currently leads at."""
        self._epoch = int(generation)

    def _check_fences(self) -> None:
        if self.write_gate is not None:
            leader = self.write_gate()
            if leader is not None:
                raise NotLeader(leader)
        if self.epoch_source is not None and self._epoch is not None:
            current = int(self.epoch_source())
            if current > self._epoch:
                raise DeposedEpoch(self._epoch, current)

    def publish(self, sequences: Iterable[pb.EventSequence]) -> list[PublishedRef]:
        """Append sequences (chunked) to their jobset partitions, then fsync."""
        self._check_fences()
        # Fault drill (core/faults): BEFORE any append, so an injected
        # publish failure is all-or-nothing -- the scheduler's
        # abort-on-publish-failure discipline (txn abort + cursor rewind)
        # is what the drill exercises, not partial-append recovery.
        from armada_tpu.core import faults

        faults.check("eventlog_publish")
        refs: list[PublishedRef] = []
        for seq in sequences:
            key = jobset_key(seq.queue, seq.jobset)
            part = partition_for_key(key, self._log.num_partitions)
            now_ns = int(self._clock() * 1e9)
            # Stamp timestamps on a copy: the caller's proto stays untouched
            # (it may be retained for retries/comparison).
            stamped = pb.EventSequence()
            stamped.CopyFrom(seq)
            for ev in stamped.events:
                if ev.created_ns == 0:
                    ev.created_ns = now_ns
            for chunk in self._chunks(stamped):
                off = self._log.append(part, key, chunk.SerializeToString())
                refs.append(PublishedRef(part, off))
        self._log.flush()
        if refs:
            self._fire_wakeups({r.partition for r in refs})
        return refs

    def publish_markers(self, group_id: Optional[str] = None) -> str:
        """Write one PartitionMarker to every partition; returns the group id."""
        self._check_fences()  # markers are appends too: same fences
        group_id = group_id or uuid.uuid4().hex
        now_ns = int(self._clock() * 1e9)
        for part in range(self._log.num_partitions):
            seq = pb.EventSequence(
                queue="",
                jobset="",
                events=[
                    pb.Event(
                        created_ns=now_ns,
                        partition_marker=pb.PartitionMarker(
                            group_id=group_id, partition=part
                        ),
                    )
                ],
            )
            self._log.append(part, MARKER_KEY, seq.SerializeToString())
        self._log.flush()
        self._fire_wakeups(set(range(self._log.num_partitions)))
        return group_id

    def _chunks(self, seq: pb.EventSequence) -> Iterable[pb.EventSequence]:
        if len(seq.events) <= self._max_events:
            yield seq
            return
        for i in range(0, len(seq.events), self._max_events):
            chunk = pb.EventSequence(
                queue=seq.queue,
                jobset=seq.jobset,
                user_id=seq.user_id,
                groups=seq.groups,
            )
            chunk.events.extend(seq.events[i : i + self._max_events])
            yield chunk


class ConsumedBatch(NamedTuple):
    sequences: list[pb.EventSequence]
    # Positions to persist AFTER the batch is durably applied (ack semantics).
    next_positions: dict[int, int]
    messages: list[Message]


class Consumer:
    """A positioned reader over all partitions.

    `poll` returns decoded sequences plus the positions that become the new
    high-water mark once the caller has stored the batch -- the at-least-once
    consume -> convert -> store -> ack shape of the reference's
    IngestionPipeline (internal/common/ingest/ingestion_pipeline.go:40-79).
    """

    def __init__(
        self,
        log: EventLog,
        positions: Optional[dict[int, int]] = None,
        partitions: Optional[Sequence[int]] = None,
    ):
        """`partitions`: restrict this consumer to a subset of the log's
        partitions (a shard of the partition-parallel ingestion plane,
        ingest/shards.py); None = all of them (the serial pipeline)."""
        self._log = log
        self.partitions: tuple[int, ...] = tuple(
            range(log.num_partitions) if partitions is None else partitions
        )
        self.positions: dict[int, int] = {p: 0 for p in self.partitions}
        if positions:
            self.positions.update(
                {p: v for p, v in positions.items() if p in self.positions}
            )

    def poll(self, max_bytes_per_partition: int = 1 << 22) -> ConsumedBatch:
        sequences: list[pb.EventSequence] = []
        messages: list[Message] = []
        next_positions = dict(self.positions)
        for part in self.partitions:
            batch = self._log.read(
                part, self.positions[part], max_bytes=max_bytes_per_partition
            )
            for msg in batch:
                sequences.append(pb.EventSequence.FromString(msg.payload))
                messages.append(msg)
            if batch:
                next_positions[part] = batch[-1].next_offset
        return ConsumedBatch(sequences, next_positions, messages)

    def ack(self, next_positions: dict[int, int]) -> None:
        self.positions.update(next_positions)

    def caught_up(self) -> bool:
        return all(
            self.positions[p] >= self._log.end_offset(p)
            for p in self.partitions
        )


def wait_for_markers(
    consumer_positions: dict[int, int],
    log: EventLog,
    group_id: str,
    timeout: float = 10.0,
    poll_interval: float = 0.05,
) -> dict[int, int]:
    """Scan forward from `consumer_positions` until the marker of `group_id` is
    found in every partition, polling (up to `timeout`) for markers that are
    still in flight; returns positions just past each marker.  Used by a
    recovering scheduler to fence its reads (scheduler.go:1120)."""
    fenced: dict[int, int] = {}
    scan_from = {
        part: consumer_positions.get(part, 0) for part in range(log.num_partitions)
    }
    deadline = time.monotonic() + timeout
    while True:
        for part in range(log.num_partitions):
            if part in fenced:
                continue
            for msg in log.iter_from(part, scan_from[part]):
                # Markers carry a distinguished key, so the (possibly huge)
                # event backlog is skipped without proto-decoding it.
                if msg.key == MARKER_KEY:
                    seq = pb.EventSequence.FromString(msg.payload)
                    if any(
                        ev.WhichOneof("event") == "partition_marker"
                        and ev.partition_marker.group_id == group_id
                        for ev in seq.events
                    ):
                        fenced[part] = msg.next_offset
                        break
                scan_from[part] = msg.next_offset
        if len(fenced) == log.num_partitions:
            return fenced
        if time.monotonic() >= deadline:
            missing = sorted(set(scan_from) - set(fenced))
            raise TimeoutError(
                f"marker {group_id} not found in partitions {missing} "
                f"within {timeout}s"
            )
        time.sleep(poll_interval)
