"""Node-axis-sharded slab cache: the steady cycle's O(delta) path on a mesh.

`models/slab.DeviceDeltaCache` keeps the problem device-resident and
updated by one jitted scatter program per cycle; this subclass keeps every
slab array under its mesh NamedSharding (parallel/mesh.problem_shardings)
instead of on one chip:

* full uploads `jax.device_put` each field WITH its sharding (a 50k-node
  slab lands N/M rows per chip -- no single-chip staging copy);
* the scatter program is compiled with `out_shardings` pinned to the slab
  layout, so an O(delta) apply (and the shadow pipeline's
  `scatter_content` prefetch) scatters replicated dirty rows into the
  sharded slab WITHOUT gathering it -- GSPMD left to its own devices may
  choose a gather+scatter+reshard, which would put the whole 1M-row slab
  on one chip's HBM and tunnel every cycle;
* TRANSFER_STATS reports per-chip bytes for sharded fields
  (models/xfer.py `up_chip_bytes`).

Mesh resolution is LAZY (first apply, i.e. inside the watchdog deadline --
touching jax.devices() dials the axon tunnel) and consults the serving
ladder (parallel/serving.py) plus the watchdog: while the supervisor is
degraded to CPU this cache behaves exactly like its base class, so the
reset-hook machinery can keep swapping cache instances without caring
which rung the ladder sits on.

Divisibility is guaranteed at build time (the builders align their node
bucket to `mesh_axis_multiple()`); `_full_upload` asserts it so a
misaligned problem fails loudly as a build bug, not a GSPMD shape error
three frames deep.
"""

from __future__ import annotations

import numpy as np

from armada_tpu.models.slab import DeviceDeltaCache, _make_apply
from armada_tpu.models.xfer import TRANSFER_STATS

# One sharding-pinned scatter program PER MESH, shared by every cache
# instance on it -- the mesh analog of slab.py's module-level _APPLY.  The
# feed builds one cache per pool and REPLACES all of them on every reset
# hook (watchdog flip, each ladder rung, restore, resync); a per-instance
# jit would re-trace P pools x every transition, right in the recovery
# window.  MeshServing caches its Mesh per rung, so the same key returns
# on restore and the dict stays ladder-sized.
_SHARDED_APPLY: dict = {}


def _sharded_apply_for(mesh, shardings):
    fn = _SHARDED_APPLY.get(mesh)
    if fn is None:
        fn = _SHARDED_APPLY[mesh] = _make_apply(out_shardings=shardings)
    return fn


class MeshDeviceDeltaCache(DeviceDeltaCache):
    """DeviceDeltaCache whose resident problem is node-axis-sharded."""

    def __init__(self, serving=None):
        super().__init__()
        if serving is None:
            from armada_tpu.parallel.serving import mesh_serving

            serving = mesh_serving()
        self._serving = serving
        self._mesh = None
        self._shardings = None  # field name -> NamedSharding
        self._repl = None  # replicated NamedSharding for unnamed payloads
        self._field_shards = None  # field name -> shard count (for stats)
        self._sharded_apply = None
        # True while a _sync_mesh entry resolved "no mesh" -- pins the
        # decision for the whole apply()/scatter_content() call.
        self._none_sticky = False

    # ------------------------------------------------------------ resolve ---

    def _ensure_mesh(self):
        """The mesh this cache places on, or None (plain base behavior:
        serving disarmed/exhausted, or the watchdog degraded to CPU --
        there the base `_to_device` routes through data_device()).

        STICKY once resolved -- in EITHER direction: every
        `_to_device`/`_apply_fn` call within one apply() must see the same
        mesh (or the same absence of one), or a ladder transition / re-probe
        promotion landing mid-upload would mix old-placement residents with
        a new-placement program and force a silent GSPMD gather.
        Transitions are detected only at apply/scatter ENTRY (`_sync_mesh`,
        which re-resolves and re-pins) -- and normally never even there,
        because every transition fires the reset hooks that REPLACE this
        cache outright."""
        if self._mesh is not None:
            return self._mesh
        if self._none_sticky:
            return None
        from armada_tpu.core.watchdog import supervisor

        if supervisor().degraded:
            return None
        mesh = self._serving.serving_mesh()
        if mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        from armada_tpu.parallel.mesh import problem_shardings

        sh = problem_shardings(mesh)
        self._mesh = mesh
        self._shardings = dict(zip(sh._fields, sh))
        self._repl = NamedSharding(mesh, P())
        self._field_shards = {
            name: int(
                np.prod([mesh.shape[ax] for ax in (s.spec or ()) if ax] or [1])
            )
            for name, s in self._shardings.items()
        }
        self._sharded_apply = _sharded_apply_for(mesh, sh)
        return self._mesh

    def _sync_mesh(self) -> None:
        """Entry guard for apply()/scatter_content(): if the serving ladder
        moved since this cache resolved its mesh (a restore() racing the
        reset-hook replacement, or a degrade the hooks have not reached
        yet), drop ALL device-resident state and re-resolve -- a scatter
        compiled for the new mesh over residents sharded on the old one
        would force GSPMD to gather/reshard the whole slab silently, the
        exact hazard this module exists to prevent.  The forced full
        re-upload is the same cost every ladder transition already budgets.

        The resolution made here is PINNED for the duration of the call
        (`_none_sticky` + the resolved `_mesh`): per-field `_ensure_mesh`
        probes must not re-consult the supervisor/ladder, or a re-probe
        promotion landing mid-full-upload would shard the later fields of a
        problem whose earlier fields already landed on the CPU data_device."""
        from armada_tpu.core.watchdog import supervisor

        if self._mesh is not None:
            cur = None if supervisor().degraded else self._serving.serving_mesh()
            if cur is not self._mesh:
                self.reset()
                self._mesh = None
                self._shardings = None
                self._repl = None
                self._field_shards = None
                self._sharded_apply = None
        self._none_sticky = False
        self._none_sticky = self._ensure_mesh() is None

    def apply(self, bundle):
        self._sync_mesh()
        return super().apply(bundle)

    def scatter_content(self, **kwargs) -> bool:
        self._sync_mesh()
        return super().scatter_content(**kwargs)

    @property
    def mesh_devices(self) -> int:
        """Devices the resident slab is sharded over (0 = single-device)."""
        return 0 if self._mesh is None else int(self._mesh.devices.size)

    # -------------------------------------------------------- base hooks ----

    def _apply_fn(self):
        if self._ensure_mesh() is None:
            return super()._apply_fn()
        return self._sharded_apply

    def _to_device(self, arr, name=None):
        if self._ensure_mesh() is None:
            return super()._to_device(arr, name)
        import jax

        sh = self._shardings.get(name) if name is not None else None
        return jax.device_put(np.asarray(arr), sh if sh is not None else self._repl)

    def _count_up(self, arr, name=None) -> None:
        shards = 1
        if self._mesh is not None and name is not None and self._field_shards:
            shards = self._field_shards.get(name, 1)
        TRANSFER_STATS.count_up(np.asarray(arr).nbytes, shards=shards)

    def _full_upload(self, problem):
        mesh = self._ensure_mesh()
        if mesh is not None:
            from armada_tpu.parallel.mesh import _check_divisible

            # Build-time alignment (incremental._node_bucket / pad_problem)
            # guarantees this; tripping it mid-serve is a build bug.
            _check_divisible(problem, mesh)
        return super()._full_upload(problem)
