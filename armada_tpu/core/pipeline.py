"""The steady-cycle pipeline switch.

The shadow-pipelined cycle (round 7) hides decision-independent host work
behind the device round: while the kernel and its result transfer are in
flight, the drivers run (a) the previous cycle's decision-dependent but
problem-independent bookkeeping and (b) the next cycle's decision-
independent feed -- proto->Job conversion, submit-side table inserts, and
the slab upload of new-submit rows (IncrementalBuilder.prefetch_content).
Decisions are bit-identical either way: the pipeline only reorders work
that neither reads the round's output nor feeds its problem -- the
soundness boundary pinned by tests/test_pipeline.py.

``ARMADA_PIPELINE=0`` is the escape hatch (A/B measurement, bisection):
every pipelined call site degrades to the sequential order.  The env var is
read per call so a test can flip it with monkeypatch; ``serve
--no-pipeline`` sets it process-wide.
"""

from __future__ import annotations

import os


def pipeline_enabled() -> bool:
    """True unless ARMADA_PIPELINE=0: shadow-pipeline the steady cycle."""
    return os.environ.get("ARMADA_PIPELINE", "1") != "0"


def pool_parallel_enabled() -> bool:
    """Pool-parallel serving armed (round 17)?  ``ARMADA_POOL_PARALLEL=1``
    / ``serve --pool-parallel`` restructures the multi-pool cycle into
    dispatch/fetch phases (pool B's upload + kernel dispatch fire while
    pool A's fetch is in flight) and stacks shape-matched small pools into
    one kernel launch.  Default OFF in the library/tests -- the serial
    per-pool loop -- because arming it is a *throughput* choice; decisions
    are bit-identical either way, but only when the cycle's pools are
    certified independent (scheduler/algo.py falls back to the serial
    order per-cycle whenever they are not: shared queued candidates,
    armed rate limiters, market pools).  Read per call so tests flip it
    with monkeypatch (the ARMADA_PIPELINE discipline)."""
    return os.environ.get("ARMADA_POOL_PARALLEL", "0") not in ("0", "")


def prefetch_worthwhile() -> bool:
    """Whether the slab content prefetch pays for itself.

    The prefetch trades an extra device scatter pass for moving its upload
    off the round's critical path.  On a real accelerator the scatter is
    device-side microseconds and the H2D transfer overlaps host work (the
    tunnel is the scarce resource); on the XLA:CPU fallback the "device" IS
    the host -- the extra pass costs real milliseconds per cycle (measured
    ~96ms at 200k jobs, round 7) with no tunnel to hide.  Default:
    accelerator backends only.  ARMADA_PIPELINE_PREFETCH=1/0 overrides
    (tests pin the scatter path on CPU with 1; 0 isolates the prefetch in
    a TPU A/B)."""
    env = os.environ.get("ARMADA_PIPELINE_PREFETCH")
    if env is not None:
        return env != "0"
    from armada_tpu.core.watchdog import supervisor

    if supervisor().degraded:
        # Device loss (core/watchdog): data lives on XLA:CPU regardless of
        # what backend jax reports, so the scatter pass is pure host cost
        # with no tunnel to hide it -- same economics as the cpu branch.
        return False
    import jax

    return jax.default_backend() != "cpu"
