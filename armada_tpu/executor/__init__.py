"""The executor: the per-cluster agent reconciling scheduler decisions onto
compute, and the fake cluster used to test multi-node behavior without one.

Equivalent of the reference's `internal/executor` (application.go StartUp:42):
a lease-request loop pulls newly assigned runs from the scheduler's
ExecutorApi and submits them to the cluster; a state-reporting loop turns pod
lifecycle changes into events.  `FakeClusterContext` mirrors
internal/executor/fake/context/context.go: simulated nodes + pod lifecycle,
the middle tier of the reference's three-tier no-real-cluster test strategy
(SURVEY.md section 4).
"""

from armada_tpu.executor.cluster import ClusterContext, PodState, PodPhase
from armada_tpu.executor.fake import FakeClusterContext
from armada_tpu.executor.service import ExecutorService

__all__ = [
    "ClusterContext",
    "PodState",
    "PodPhase",
    "FakeClusterContext",
    "ExecutorService",
]
