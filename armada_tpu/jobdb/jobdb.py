"""JobDb: the in-memory job store with single-writer transactions.

Equivalent of the reference's jobdb (internal/scheduler/jobdb/jobdb.go:67-84,
305-324): jobs indexed by id, run id and gang key, plus a per-queue ordered
set of queued jobs iterated in scheduling order; WriteTxn buffers updates that
become visible only on Commit (single writer, enforced by a lock held for the
txn's lifetime); Txn.Assert checks cross-index invariants (jobdb.go:387).

Concurrency model: one writer at a time; readers read committed state.  A
write txn's uncommitted changes are visible only through that txn (overlay
reads), and Abort discards them -- the property the scheduler cycle depends on
(scheduler.go cycle: schedule against a txn, publish, then commit).  Point
reads are lock-free; iteration methods materialize a consistent snapshot under
a short state lock also taken by commit, so readers never observe a
half-applied commit.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

try:
    from sortedcontainers import SortedKeyList
except ImportError:  # not in every toolchain; same-semantics local subset
    from armada_tpu.jobdb._sortedlist import SortedKeyList

from armada_tpu.analysis.tsan import make_lock
from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.ordering import scheduling_order_key
from armada_tpu.jobdb.job import Job, JobRun


def _order_key(config: SchedulingConfig) -> Callable[[Job], tuple]:
    def key(job: Job) -> tuple:
        pc = job.priority_class(config)
        return scheduling_order_key(pc.priority, job.priority, job.submitted_ns, job.id)

    return key


def market_order_key(bid_price_of: Callable[[Job], float]) -> Callable[[Job], tuple]:
    """Market scheduling order (jobdb/comparison.go MarketJobPriorityComparer):
    higher bid price first, then earlier submission."""

    def key(job: Job) -> tuple:
        return (-bid_price_of(job), job.submitted_ns, job.id)

    return key


def gang_key(job: Job) -> Optional[tuple[str, str]]:
    return (job.queue, job.spec.gang_id) if job.spec.gang_id else None


class JobDb:
    def __init__(
        self,
        config: Optional[SchedulingConfig] = None,
        order_key: Optional[Callable[[Job], tuple]] = None,
    ):
        """`order_key` overrides the queued-job ordering (e.g. market_order_key
        for price-ordered pools, jobdb/comparison.go MarketJobPriorityComparer)."""
        from armada_tpu.core.config import default_scheduling_config

        self.config = config or default_scheduling_config()
        self._jobs: dict[str, Job] = {}
        self._job_by_run: dict[str, str] = {}
        self._by_gang: dict[tuple[str, str], set[str]] = {}
        self._queued: dict[str, SortedKeyList] = {}
        self._unvalidated: set[str] = set()
        self._order = order_key or _order_key(self.config)
        self._writer = make_lock("jobdb.writer")
        # Guards in-place index mutation during _apply against concurrent
        # reader iteration (readers snapshot under this lock).
        self._state = make_lock("jobdb.state")
        # Commit subscribers: fn(upserts: dict[str, Job], deletes: set[str]),
        # called after each committed txn -- the delta feed for the
        # incremental problem builder (scheduler/incremental_algo.py), the
        # analog of the reference's scheduler keeping its jobDb between
        # cycles (scheduler.go:240-246).  Callbacks run under the writer
        # lock; they must not open txns.  Abort subscribers fire when a txn
        # with buffered changes is discarded: anyone who peeked at the
        # overlay (the feed does, at schedule time) must resynchronize from
        # committed state.
        self._subscribers: list = []
        self._abort_subscribers: list = []

    def subscribe(self, fn) -> None:
        self._subscribers.append(fn)

    def subscribe_abort(self, fn) -> None:
        self._abort_subscribers.append(fn)

    # --- transactions -------------------------------------------------------

    def read_txn(self) -> "ReadTxn":
        return ReadTxn(self)

    def write_txn(self) -> "WriteTxn":
        self._writer.acquire()
        return WriteTxn(self)

    # --- committed-state accessors (used by txns) ---------------------------

    def _get(self, job_id: str) -> Optional[Job]:
        return self._jobs.get(job_id)

    def _apply(self, upserts: dict[str, Job], deletes: set[str]) -> None:
        """Apply a txn's buffered changes to the committed indexes.

        The ordering key (which resolves priority classes, the only thing
        that can raise here) was already evaluated per job by
        WriteTxn.upsert, and Jobs are immutable -- so by the time a commit
        reaches this point nothing can fail mid-mutation, and re-validating
        a 1k-upsert batch would just re-pay a third of the commit's cost.
        """
        with self._state:
            for job_id in deletes:
                old = self._jobs.pop(job_id, None)
                if old is not None:
                    self._deindex(old)
            for job_id, job in upserts.items():
                old = self._jobs.get(job_id)
                if old is not None:
                    self._deindex(old)
                self._jobs[job_id] = job
                self._index(job)
        for fn in self._subscribers:
            fn(upserts, deletes)

    def _index(self, job: Job) -> None:
        for run in job.runs:
            self._job_by_run[run.id] = job.id
        gk = gang_key(job)
        if gk is not None:
            self._by_gang.setdefault(gk, set()).add(job.id)
        if job.queued:
            self._queued.setdefault(
                job.queue, SortedKeyList(key=self._order)
            ).add(job)
        if not job.validated and not job.in_terminal_state():
            self._unvalidated.add(job.id)

    def _deindex(self, job: Job) -> None:
        for run in job.runs:
            self._job_by_run.pop(run.id, None)
        gk = gang_key(job)
        if gk is not None:
            ids = self._by_gang.get(gk)
            if ids is not None:
                ids.discard(job.id)
                if not ids:
                    del self._by_gang[gk]
        if job.queued:
            queued = self._queued.get(job.queue)
            if queued is not None:
                queued.discard(job)
        self._unvalidated.discard(job.id)


class ReadTxn:
    """Reads committed state.  Kept as an object (rather than bare db methods)
    so read and write paths share one accessor interface."""

    def __init__(self, db: JobDb):
        self._db = db

    def get(self, job_id: str) -> Optional[Job]:
        return self._db._get(job_id)

    def get_by_run_id(self, run_id: str) -> Optional[Job]:
        # Two-step read: must not interleave with _apply's deindex/reindex.
        with self._db._state:
            job_id = self._db._job_by_run.get(run_id)
            return self._db._get(job_id) if job_id else None

    def gang_jobs(self, queue: str, gang_id: str) -> list[Job]:
        with self._db._state:
            ids = sorted(self._db._by_gang.get((queue, gang_id), set()))
            return [self._db._jobs[i] for i in ids]

    def queued_jobs(self, queue: str) -> list[Job]:
        """Queued jobs of a queue in scheduling order (jobdb.go QueuedJobs:703).

        Returns a snapshot list: safe against concurrent commits.
        """
        with self._db._state:
            return list(self._db._queued.get(queue, ()))

    def unvalidated_jobs(self) -> list[Job]:
        with self._db._state:
            return [self._db._jobs[i] for i in sorted(self._db._unvalidated)]

    def queues_with_queued_jobs(self) -> list[str]:
        with self._db._state:
            return sorted(q for q, s in self._db._queued.items() if len(s) > 0)

    def all_jobs(self) -> list[Job]:
        with self._db._state:
            return list(self._db._jobs.values())

    def __len__(self) -> int:
        return len(self._db._jobs)


class WriteTxn(ReadTxn):
    """Buffered single-writer transaction: reads see the overlay; Commit
    publishes atomically; Abort discards.  Mirrors jobdb.Txn (jobdb.go:305-324)."""

    def __init__(self, db: JobDb):
        super().__init__(db)
        self._upserts: dict[str, Job] = {}
        self._deletes: set[str] = set()
        self._touched_cache: Optional[set[str]] = None
        self._done = False

    # --- overlay reads ------------------------------------------------------

    def get(self, job_id: str) -> Optional[Job]:
        if job_id in self._deletes:
            return None
        if job_id in self._upserts:
            return self._upserts[job_id]
        return self._db._get(job_id)

    def get_by_run_id(self, run_id: str) -> Optional[Job]:
        for job in self._upserts.values():
            if any(r.id == run_id for r in job.runs):
                return job
        job = super().get_by_run_id(run_id)
        if job is None or job.id in self._deletes:
            return None
        return self.get(job.id)

    def gang_jobs(self, queue: str, gang_id: str) -> list[Job]:
        ids = set(self._db._by_gang.get((queue, gang_id), set()))
        for job in self._upserts.values():
            if gang_key(job) == (queue, gang_id):
                ids.add(job.id)
        ids -= self._deletes
        return [j for i in sorted(ids) if (j := self.get(i)) is not None]

    def _touched_queues(self) -> set[str]:
        """Queues whose committed queued-index the overlay could alter.
        Cached; invalidated by upsert/delete."""
        if self._touched_cache is not None:
            return self._touched_cache
        queues: set[str] = set()
        for job_id, job in self._upserts.items():
            queues.add(job.queue)
            old = self._db._get(job_id)
            if old is not None:
                queues.add(old.queue)
        for job_id in self._deletes:
            old = self._db._get(job_id)
            if old is not None:
                queues.add(old.queue)
        self._touched_cache = queues
        return queues

    def queued_jobs(self, queue: str) -> list[Job]:
        if queue not in self._touched_queues():
            return super().queued_jobs(queue)
        # Merge the committed ordered set with the overlay.
        touched = set(self._upserts) | self._deletes
        merged = SortedKeyList(key=self._db._order)
        for job in super().queued_jobs(queue):
            if job.id not in touched:
                merged.add(job)
        for job in self._upserts.values():
            if job.queue == queue and job.queued:
                merged.add(job)
        return list(merged)

    def _queue_has_queued(self, queue: str) -> bool:
        """Emptiness check without materializing the overlay merge."""
        touched = set(self._upserts) | self._deletes
        for job in self._upserts.values():
            if job.queue == queue and job.queued:
                return True
        return any(
            job.id not in touched for job in super().queued_jobs(queue)
        )

    def queues_with_queued_jobs(self) -> list[str]:
        queues = set(super().queues_with_queued_jobs())
        for job in self._upserts.values():
            if job.queued:
                queues.add(job.queue)
        touched = self._touched_queues()
        # Only queues the overlay touches can have become empty; others keep
        # their committed answer.
        return sorted(
            q for q in queues if q not in touched or self._queue_has_queued(q)
        )

    def unvalidated_jobs(self) -> list[Job]:
        ids = set(self._db._unvalidated)
        for job in self._upserts.values():
            if not job.validated and not job.in_terminal_state():
                ids.add(job.id)
            else:
                ids.discard(job.id)
        ids -= self._deletes
        return [j for i in sorted(ids) if (j := self.get(i)) is not None]

    def all_jobs(self) -> list[Job]:
        out = [
            job
            for job_id, job in self._db._jobs.items()
            if job_id not in self._deletes and job_id not in self._upserts
        ]
        out.extend(self._upserts.values())
        return out

    def __len__(self) -> int:
        n = len(self._db._jobs)
        n -= len(self._deletes & set(self._db._jobs))
        n += len(set(self._upserts) - set(self._db._jobs))
        return n

    # --- writes -------------------------------------------------------------

    def upsert(self, jobs: "Job | Iterable[Job]") -> None:
        self._check_active()
        if isinstance(jobs, Job):
            jobs = [jobs]
        self._touched_cache = None
        for job in jobs:
            self._db._order(job)  # fail fast on unknown priority class
            self._deletes.discard(job.id)
            self._upserts[job.id] = job

    def delete(self, job_ids: "str | Iterable[str]") -> None:
        self._check_active()
        if isinstance(job_ids, str):
            job_ids = [job_ids]
        self._touched_cache = None
        for job_id in job_ids:
            self._upserts.pop(job_id, None)
            self._deletes.add(job_id)

    def commit(self) -> None:
        self._check_active()
        try:
            self._db._apply(self._upserts, self._deletes)
        except BaseException:
            # Pre-validation failed: committed state is untouched; release the
            # writer so the failure can't deadlock the next txn.
            self._finish()
            raise
        self._finish()

    def abort(self) -> None:
        if not self._done:
            had_changes = bool(self._upserts or self._deletes)
            self._finish()
            if had_changes:
                for fn in self._db._abort_subscribers:
                    fn()

    def _finish(self) -> None:
        self._done = True
        self._upserts = {}
        self._deletes = set()
        self._db._writer.release()

    def _check_active(self) -> None:
        if self._done:
            raise ValueError("transaction already committed or aborted")

    def __enter__(self) -> "WriteTxn":
        return self

    def __exit__(self, exc_type, *_) -> None:
        if not self._done:
            if exc_type is None:
                self.commit()
            else:
                self.abort()

    # --- invariants (jobdb.Txn.Assert, jobdb.go:387) ------------------------

    def assert_invariants(self) -> None:
        """Raise AssertionError on cross-field/index inconsistencies."""
        for job in self.all_jobs():
            state = (
                f"job {job.id}: queued={job.queued} "
                f"terminal={job.in_terminal_state()} runs={len(job.runs)}"
            )
            if job.queued and job.in_terminal_state():
                raise AssertionError(f"{state}: queued but terminal")
            if job.queued and job.has_active_run():
                raise AssertionError(f"{state}: queued with active run")
            if job.succeeded and not any(r.succeeded for r in job.runs):
                raise AssertionError(f"{state}: succeeded without succeeded run")
            run_ids = [r.id for r in job.runs]
            if len(run_ids) != len(set(run_ids)):
                raise AssertionError(f"{state}: duplicate run ids")
            for run in job.runs:
                if run.job_id != job.id:
                    raise AssertionError(
                        f"{state}: run {run.id} claims job {run.job_id}"
                    )
