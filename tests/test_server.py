"""Submit server, queue repository, event API tests.

Modeled on the reference's internal/server/submit tests (submit_test.go,
validation tests) and event repository tests.
"""

import threading

import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.eventlog import EventLog
from armada_tpu.eventlog.publisher import Consumer, Publisher
from armada_tpu.events import events_pb2 as pb
from armada_tpu.ingest.converter import convert_sequences
from armada_tpu.ingest.pipeline import IngestionPipeline
from armada_tpu.ingest.schedulerdb import SchedulerDb
from armada_tpu.server import (
    ActionAuthorizer,
    EventApi,
    EventDb,
    JobSubmitItem,
    Permission,
    Principal,
    QueueRecord,
    QueueRepository,
    SubmitServer,
    SubmitError,
    event_sink_converter,
)
from armada_tpu.server.auth import AuthorizationError


@pytest.fixture
def stack(tmp_path):
    log = EventLog(str(tmp_path / "log"), num_partitions=2)
    db = SchedulerDb(":memory:")
    queues = QueueRepository(db)
    server = SubmitServer(db, Publisher(log), queues, SchedulingConfig(shape_bucket=32))
    pipeline = IngestionPipeline(log, db, convert_sequences, consumer_name="scheduler")
    yield log, db, queues, server, pipeline
    db.close()
    log.close()


def item(cpu="1", **kw):
    return JobSubmitItem(resources={"cpu": cpu, "memory": "1"}, **kw)


# --- queues ------------------------------------------------------------------


def test_queue_crud(stack):
    _, _, queues, server, _ = stack
    server.create_queue(QueueRecord("q1", weight=2.5, owners=("alice",)))
    assert server.get_queue("q1").weight == 2.5
    with pytest.raises(ValueError):
        server.create_queue(QueueRecord("q1"))
    server.update_queue(QueueRecord("q1", weight=3.0))
    assert server.get_queue("q1").weight == 3.0
    with pytest.raises(KeyError):
        server.update_queue(QueueRecord("nope"))
    server.create_queue(QueueRecord("q2"))
    assert [q.name for q in server.list_queues()] == ["q1", "q2"]
    server.delete_queue("q2")
    assert [q.name for q in server.list_queues()] == ["q1"]
    # cordoned queues drop out of the scheduling view but stay listed
    server.update_queue(QueueRecord("q1", cordoned=True))
    assert queues.scheduling_queues() == []


def test_queue_validation(stack):
    _, _, _, server, _ = stack
    with pytest.raises(ValueError):
        server.create_queue(QueueRecord("bad", weight=0))
    with pytest.raises(ValueError):
        server.create_queue(QueueRecord(""))


# --- submission --------------------------------------------------------------


def test_submit_publishes_and_materializes(stack):
    _, db, _, server, pipeline = stack
    server.create_queue(QueueRecord("q1"))
    ids = server.submit_jobs("q1", "js1", [item(), item(cpu="2")])
    assert len(ids) == 2 and len(set(ids)) == 2
    pipeline.run_until_caught_up()
    rows, _ = db.fetch_job_updates(0, 0)
    assert {r["job_id"] for r in rows} == set(ids)
    assert all(r["queue"] == "q1" and r["jobset"] == "js1" for r in rows)


def test_submit_requires_existing_queue(stack):
    _, _, _, server, _ = stack
    with pytest.raises(SubmitError, match="does not exist"):
        server.submit_jobs("ghost", "js", [item()])


def test_submit_validation_errors(stack):
    _, _, _, server, _ = stack
    server.create_queue(QueueRecord("q1"))
    cases = [
        ([], "empty"),
        ([JobSubmitItem(resources={})], "no resources"),
        ([JobSubmitItem(resources={"quantum-flux": 1})], "unsupported resource"),
        ([JobSubmitItem(resources={"cpu": 0, "memory": 0})], "all-zero"),
        ([item(priority=-1)], "priority"),
        ([item(priority_class="vip")], "unknown priority class"),
        ([item(gang_cardinality=3)], "without gang_id"),
        (
            [item(gang_id="g", gang_cardinality=2), item(gang_id="g", gang_cardinality=3)],
            "cardinality",
        ),
        (
            [
                item(gang_id="g", gang_cardinality=1),
                item(gang_id="g", gang_cardinality=1),
            ],
            "members submitted",
        ),
        # under-submitted gang can never complete -> rejected up front
        ([item(gang_id="g", gang_cardinality=3)], "members submitted"),
        ([item(client_id="c"), item(client_id="c")], "duplicate client_id"),
    ]
    for items, match in cases:
        with pytest.raises(SubmitError, match=match):
            server.submit_jobs("q1", "js", items)


def test_submit_dedup_by_client_id(stack):
    log, db, _, server, pipeline = stack
    server.create_queue(QueueRecord("q1"))
    ids1 = server.submit_jobs("q1", "js", [item(client_id="req-1")])
    ids2 = server.submit_jobs("q1", "js", [item(client_id="req-1"), item(client_id="req-2")])
    assert ids2[0] == ids1[0]  # deduped
    assert ids2[1] != ids1[0]
    pipeline.run_until_caught_up()
    rows, _ = db.fetch_job_updates(0, 0)
    # only two distinct jobs ever created
    assert len(rows) == 2


def test_cancel_preempt_reprioritize_roundtrip(stack):
    _, db, _, server, pipeline = stack
    server.create_queue(QueueRecord("q1"))
    ids = server.submit_jobs("q1", "js", [item(), item(), item()])
    pipeline.run_until_caught_up()

    server.cancel_jobs("q1", "js", [ids[0]], reason="user")
    server.reprioritize_jobs("q1", "js", priority=7, job_ids=[ids[1]])
    pipeline.run_until_caught_up()
    rows, _ = db.fetch_job_updates(0, 0)
    by_id = {r["job_id"]: r for r in rows}
    assert by_id[ids[0]]["cancel_requested"] == 1
    assert by_id[ids[1]]["priority"] == 7

    # jobset-wide reprioritisation
    server.reprioritize_jobs("q1", "js", priority=9)
    pipeline.run_until_caught_up()
    rows, _ = db.fetch_job_updates(0, 0)
    assert all(r["priority"] == 9 for r in rows)

    # preemption of a job with no run yet persists on the job row, so the
    # scheduler can act on it whenever the job's fate is decided
    server.preempt_jobs("q1", "js", [ids[2]])
    pipeline.run_until_caught_up()
    rows, _ = db.fetch_job_updates(0, 0)
    by_id = {r["job_id"]: r for r in rows}
    assert by_id[ids[2]]["preempt_requested"] == 1


def test_cancel_jobset_states_validated(stack):
    _, _, _, server, _ = stack
    server.create_queue(QueueRecord("q1"))
    with pytest.raises(SubmitError, match="invalid jobset-cancel state"):
        server.cancel_jobset("q1", "js", states=["sleeping"])
    server.cancel_jobset("q1", "js", states=["queued"])  # ok


def test_closed_authorizer_enforces_acls(stack):
    _, db, queues, _, _ = stack
    log2 = None
    server = SubmitServer(
        db,
        # publisher unused before auth check fails
        publisher=None,
        queues=queues,
        authorizer=ActionAuthorizer(open_by_default=False),
    )
    with pytest.raises(AuthorizationError):
        server.create_queue(QueueRecord("q1"), Principal("mallory"))
    admin = Principal("root", permissions=frozenset({Permission.CREATE_QUEUE}))
    server.create_queue(QueueRecord("q1", owners=("alice",), groups=("team",)), admin)
    # owner may act via queue ACL; group member passes, stranger fails
    alice = Principal("alice")
    bob = Principal("bob", groups=("team",))
    with pytest.raises(AuthorizationError):
        server.cancel_jobs("q1", "js", ["x"], principal=Principal("mallory"))
    # publishing needs a real publisher; swap in a recorder
    class Rec:
        def __init__(self):
            self.seqs = []

        def publish(self, seqs):
            self.seqs.extend(seqs)

    server._publisher = Rec()
    server.cancel_jobs("q1", "js", ["x"], principal=alice)
    server.cancel_jobs("q1", "js", ["x"], principal=bob)
    assert len(server._publisher.seqs) == 2


# --- event streams -----------------------------------------------------------


def test_event_stream_materialization_and_watch(stack, tmp_path):
    log, db, _, server, pipeline = stack
    server.create_queue(QueueRecord("q1"))
    eventdb = EventDb(":memory:")
    event_pipeline = IngestionPipeline(
        log, eventdb, event_sink_converter, consumer_name="events"
    )
    api = EventApi(eventdb)

    ids = server.submit_jobs("q1", "js", [item(), item()])
    server.cancel_jobs("q1", "js", [ids[0]])
    event_pipeline.run_until_caught_up()

    got = api.get_jobset_events("q1", "js")
    kinds = [
        ev.WhichOneof("event") for e in got for ev in e.sequence.events
    ]
    assert kinds.count("submit_job") == 2
    assert kinds.count("cancel_job") == 1

    # resume from an index: only later events
    later = api.get_jobset_events("q1", "js", from_idx=got[-1].idx)
    assert len(later) == 1

    # watch sees live appends
    stop = threading.Event()
    seen = []

    def consume():
        for item_ in api.watch("q1", "js", poll_interval_s=0.01, stop=stop, idle_timeout_s=2.0):
            seen.append(item_)
            if len(seen) >= 3:
                stop.set()

    t = threading.Thread(target=consume)
    t.start()
    server.submit_jobs("q1", "js", [item()])
    event_pipeline.run_until_caught_up()
    t.join(timeout=5)
    stop.set()
    assert len(seen) >= 3
    eventdb.close()


def test_event_streams_isolated_per_jobset(stack):
    log, db, _, server, pipeline = stack
    server.create_queue(QueueRecord("q1"))
    eventdb = EventDb(":memory:")
    event_pipeline = IngestionPipeline(
        log, eventdb, event_sink_converter, consumer_name="events"
    )
    api = EventApi(eventdb)
    server.submit_jobs("q1", "js-a", [item()])
    server.submit_jobs("q1", "js-b", [item(), item()])
    event_pipeline.run_until_caught_up()
    assert len(api.get_jobset_events("q1", "js-a")) == 1
    assert len(api.get_jobset_events("q1", "js-b")) == 1  # one sequence, 2 events
    evs = api.get_jobset_events("q1", "js-b")[0].sequence.events
    assert len(evs) == 2
    eventdb.close()


def test_event_retention_prune(stack):
    log, db, _, server, pipeline = stack
    server.create_queue(QueueRecord("q1"))
    eventdb = EventDb(":memory:", retention_s=60.0)
    event_pipeline = IngestionPipeline(
        log, eventdb, event_sink_converter, consumer_name="events"
    )
    server.submit_jobs("q1", "js", [item()])
    event_pipeline.run_until_caught_up()
    rows = eventdb.read("q1", "js")
    created = rows[0]["created_ns"]
    assert eventdb.prune(created + int(30e9)) == 0
    assert eventdb.prune(created + int(120e9)) == 1
    assert eventdb.read("q1", "js") == []

    # Stream indices stay monotonic across pruning: a watcher cursor that
    # advanced past the pruned rows still sees everything new.
    server.submit_jobs("q1", "js", [item()])
    event_pipeline.run_until_caught_up()
    rows = eventdb.read("q1", "js")
    assert rows and rows[0]["idx"] == 1  # not reset to 0
    eventdb.close()
