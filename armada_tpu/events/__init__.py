"""Event schema (protobuf) + conversion helpers.

`events_pb2` is regenerated from events.proto with protoc when the .proto is
newer than the generated module (protoc is part of the baked toolchain; when
the binary is absent, the pure-python subset compiler in `_minigen` produces
an equivalent module).
"""

from __future__ import annotations

import fcntl
import os
import shutil
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_PROTO = os.path.join(_HERE, "events.proto")
_GEN = os.path.join(_HERE, "events_pb2.py")

if not os.path.exists(_GEN) or os.path.getmtime(_PROTO) > os.path.getmtime(_GEN):
    # Generate into a temp dir and os.replace into place under a file lock, so
    # concurrent first-importers never see a partially-written module.
    with open(_GEN + ".lock", "w") as _lockf:
        fcntl.flock(_lockf, fcntl.LOCK_EX)
        if not os.path.exists(_GEN) or os.path.getmtime(_PROTO) > os.path.getmtime(_GEN):
            with tempfile.TemporaryDirectory(dir=_HERE) as _tmp:
                _tmp_gen = os.path.join(_tmp, "events_pb2.py")
                if shutil.which("protoc"):
                    subprocess.run(
                        ["protoc", "-I", _HERE, f"--python_out={_tmp}", _PROTO],
                        check=True,
                    )
                else:
                    from armada_tpu.events import _minigen

                    with open(_tmp_gen, "w") as _f:
                        _f.write(
                            _minigen.generate_pb2_source(
                                _PROTO, "events.proto", "events_pb2"
                            )
                        )
                # lint: allow(atomic-state-file) -- generated CODE module,
                # not durable state: must stay plainly importable, and a
                # lost regen just re-runs on the next import.
                os.replace(_tmp_gen, _GEN)

from armada_tpu.events import events_pb2  # noqa: E402

from armada_tpu.events.convert import (  # noqa: E402
    job_spec_from_proto,
    job_spec_to_proto,
    resources_from_proto,
    resources_to_proto,
)

__all__ = [
    "events_pb2",
    "job_spec_from_proto",
    "job_spec_to_proto",
    "resources_from_proto",
    "resources_to_proto",
]
