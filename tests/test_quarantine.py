"""Node quarantine: high-failure-rate nodes leave scheduling (README.md:28)."""

import pytest

from armada_tpu.core.config import SchedulingConfig, scheduling_config_from_dict
from armada_tpu.scheduler.quarantine import NodeQuarantine
from tests.control_plane import ControlPlane
from armada_tpu.server import JobSubmitItem, QueueRecord

S = int(1e9)


def test_threshold_window_and_cooldown():
    q = NodeQuarantine(failure_threshold=3, window_s=60, cooldown_s=120)
    assert not q.record_failure("n0", 0)
    assert not q.record_failure("n0", 10 * S)
    # third failure inside the window trips it
    assert q.record_failure("n0", 20 * S)
    assert q.quarantined(21 * S) == {"n0"}
    # cooldown readmits
    assert q.quarantined(20 * S + 121 * S) == frozenset()
    # failures outside the window don't accumulate
    q2 = NodeQuarantine(failure_threshold=3, window_s=60, cooldown_s=120)
    q2.record_failure("n1", 0)
    q2.record_failure("n1", 70 * S)
    assert not q2.record_failure("n1", 140 * S)
    assert q2.quarantined(141 * S) == frozenset()


def test_disabled_records_nothing():
    q = NodeQuarantine(failure_threshold=0)
    assert not q.record_failure("n0", 0)
    assert q.quarantined(1) == frozenset()


def test_yaml_knobs():
    cfg = scheduling_config_from_dict(
        {
            "nodeQuarantineFailureThreshold": 5,
            "nodeQuarantineWindow": "2m",
            "nodeQuarantineCooldown": "10m",
        }
    )
    assert cfg.node_quarantine_failure_threshold == 5
    assert cfg.node_quarantine_window_s == 120.0
    assert cfg.node_quarantine_cooldown_s == 600.0


def test_failing_node_is_quarantined_end_to_end(tmp_path):
    """Two pods die on n0 -> n0 quarantined -> next job lands on n1 even
    though n0 is emptier; after the cooldown n0 is schedulable again."""
    cfg = SchedulingConfig(
        shape_bucket=32,
        enable_assertions=True,
        node_quarantine_failure_threshold=2,
        node_quarantine_window_s=600.0,
        node_quarantine_cooldown_s=300.0,
    )
    cp = ControlPlane.build(
        tmp_path,
        config=cfg,
        executor_specs={"ex1": (2, "8", "32")},
        runtime_s=1000.0,
    )
    cp.server.create_queue(QueueRecord("q"))
    ex = cp.executors[0]

    def submit_and_place(name):
        (jid,) = cp.server.submit_jobs(
            "q", "js", [JobSubmitItem(resources={"cpu": "2", "memory": "2"})]
        )
        ex.run_once()
        cp.ingest()
        cp.scheduler.cycle()
        cp.ingest()
        ex.run_once()
        run = cp.jobdb.read_txn().get(jid).latest_run
        return jid, run.id, run.node_id

    # two jobs fail on whichever node they land (best-fit packs both on the
    # same emptier node... they land on ex1-n0 both times)
    for _ in range(2):
        jid, rid, nid = submit_and_place("victim")
        assert nid == "ex1-n0"
        ex.cluster.tick(0.5)  # running -> attempted
        ex.report_cycle()
        cp.ingest()
        cp.scheduler.cycle()
        ex.cluster.fail_pod(rid, "disk on fire")
        ex.report_cycle()
        ex.cleanup()
        cp.ingest()
        cp.scheduler.cycle()

    assert cp.scheduler.node_quarantine.quarantined(cp.scheduler.now_ns()) == {
        "ex1-n0"
    }

    # next job avoids the quarantined node
    jid3, _, nid3 = submit_and_place("survivor")
    assert nid3 == "ex1-n1"

    # cooldown readmits n0: the tracker clears, and a node-filling job that
    # cannot fit next to the survivor on n1 lands on n0 again
    cp.clock.advance(400.0)
    assert (
        cp.scheduler.node_quarantine.quarantined(cp.scheduler.now_ns())
        == frozenset()
    )
    (big,) = cp.server.submit_jobs(
        "q", "js", [JobSubmitItem(resources={"cpu": "8", "memory": "2"})]
    )
    ex.run_once()
    cp.ingest()
    cp.scheduler.cycle()
    run = cp.jobdb.read_txn().get(big).latest_run
    assert run is not None and run.node_id == "ex1-n0"
    cp.close()
